"""swarm-rafttool renewcert: offline certificate renewal from a downed
manager's state dir (VERDICT r03 item 8; reference
swarmd/cmd/swarm-rafttool/renewcert.go:16-101).

The disaster path: a manager was down long enough for its TLS cert to
expire — it can no longer dial any CA server, so the cert is re-issued
offline from the CA material in its own raft log, and the node rejoins.
"""
import datetime
import os
import time

import pytest

from swarmkit_tpu.agent.testutils import FakeExecutor
from swarmkit_tpu.api.specs import Annotations, ServiceSpec
from swarmkit_tpu.api.types import TaskState
from swarmkit_tpu.ca.certificates import parse_cert_identity
from swarmkit_tpu.cmd import rafttool
from swarmkit_tpu.node.daemon import SwarmNode
from swarmkit_tpu.rpc.services import RemoteControl
from swarmkit_tpu.store import by as by_mod

from test_scheduler import wait_for

pytestmark = pytest.mark.daemon


def _expired_leaf(root, node_id: str, role: int, org: str) -> bytes:
    """A leaf for `node_id` signed by `root` that expired yesterday —
    sign_csr clamps expiry to a sane minimum, so build it directly."""
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.x509.oid import NameOID

    from swarmkit_tpu.ca.certificates import (
        generate_key,
        key_from_pem,
        role_to_ou,
    )

    key = generate_key()
    now = datetime.datetime.now(datetime.timezone.utc)
    subject = x509.Name([
        x509.NameAttribute(NameOID.COMMON_NAME, node_id),
        x509.NameAttribute(NameOID.ORGANIZATIONAL_UNIT_NAME,
                           role_to_ou(role)),
        x509.NameAttribute(NameOID.ORGANIZATION_NAME, org),
    ])
    issuer = x509.load_pem_x509_certificates(root.cert_pem)[0].subject
    cert = (
        x509.CertificateBuilder()
        .subject_name(subject)
        .issuer_name(issuer)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(days=30))
        .not_valid_after(now - datetime.timedelta(days=1))
        .sign(key_from_pem(root.key_pem), hashes.SHA256())
    )
    return cert.public_bytes(serialization.Encoding.PEM)


def _create_with_retry(ctl, spec, timeout=60):
    """Operator-grade create: a starved host can stretch one RPC past its
    30 s call timeout while the write actually committed — retry and
    treat AlreadyExists as success (the name is the idempotency key)."""
    import time as _time

    from swarmkit_tpu.controlapi.errors import AlreadyExists

    deadline = _time.monotonic() + timeout
    while True:
        try:
            return ctl.create_service(spec)
        except AlreadyExists:
            for s in ctl.list_services():
                if s.spec.annotations.name == spec.annotations.name:
                    return s
            raise
        except Exception:
            if _time.monotonic() >= deadline:
                raise
            _time.sleep(1.0)


def test_renewcert_offline_then_rejoin(tmp_path):
    node = SwarmNode(
        state_dir=str(tmp_path / "m1"),
        executor=FakeExecutor({"*": {"run_forever": True}}, hostname="m1"),
        listen_addr="127.0.0.1:0",
        heartbeat_period=0.5,
        tick_interval=0.05,
    )
    node.start()
    try:
        assert wait_for(lambda: node.is_leader, timeout=15)
        ctl = RemoteControl(node.addr, node.security)
        try:
            svc = _create_with_retry(ctl, ServiceSpec(
                annotations=Annotations(name="pre-down"), replicas=1))
        finally:
            ctl.close()

        def running():
            tasks = node.store.view(
                lambda tx: tx.find_tasks(by_mod.ByServiceID(svc.id)))
            return [t for t in tasks
                    if t.status.state == TaskState.RUNNING]

        assert wait_for(lambda: len(running()) == 1, timeout=45)
        node_id = node.node_id
        root = node.manager.ca_server.root          # has the signing key
    finally:
        node.stop()
    time.sleep(0.5)

    state_dir = str(tmp_path / "m1")
    cert_path = os.path.join(state_dir, "cert.pem")
    with open(cert_path, "rb") as f:
        old_cert = f.read()
    ident = parse_cert_identity(old_cert)
    assert ident.node_id == node_id

    # the disaster: the cert expired while the node was down
    with open(cert_path, "wb") as f:
        f.write(_expired_leaf(root, ident.node_id, ident.role, ident.org))
    from swarmkit_tpu.ca import RootCA
    from swarmkit_tpu.ca.certificates import CertificateError

    with open(os.path.join(state_dir, "ca.pem"), "rb") as f:
        anchor = RootCA(f.read())
    with open(cert_path, "rb") as f:
        with pytest.raises(CertificateError):
            anchor.verify_cert(f.read())            # really expired

    # offline renewal from the raft log
    rc = rafttool.main(["renewcert", "--state-dir", state_dir])
    assert rc == 0

    # identity preserved, cert now valid, key file headers intact
    with open(cert_path, "rb") as f:
        renewed = f.read()
    new_ident = anchor.verify_cert(renewed)
    assert (new_ident.node_id, new_ident.role, new_ident.org) == \
        (ident.node_id, ident.role, ident.org)
    from swarmkit_tpu.ca import KeyReadWriter

    _key, headers = KeyReadWriter(
        os.path.join(state_dir, "key.json")).read()
    assert headers.get("raft-dek")                  # DEK survived renewal

    # the node rejoins from the renewed identity and serves again
    # fresh port: a lone manager re-elects itself regardless of the
    # advertised address recorded in its own membership entry
    back = SwarmNode(
        state_dir=state_dir,
        executor=FakeExecutor({"*": {"run_forever": True}},
                              hostname="m1"),
        listen_addr="127.0.0.1:0",
        heartbeat_period=0.5,
        tick_interval=0.05,
    )
    back.start()
    try:
        assert back.node_id == node_id
        assert wait_for(lambda: back.is_leader, timeout=30)
        ctl = RemoteControl(back.addr, back.security)
        try:
            svc2 = _create_with_retry(ctl, ServiceSpec(
                annotations=Annotations(name="post-renew"), replicas=1))
        finally:
            ctl.close()

        def running2():
            tasks = back.store.view(
                lambda tx: tx.find_tasks(by_mod.ByServiceID(svc2.id)))
            return [t for t in tasks
                    if t.status.state == TaskState.RUNNING]

        assert wait_for(lambda: len(running2()) == 1, timeout=45)
    finally:
        back.stop()
