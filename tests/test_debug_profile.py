"""/debug/profile (node/debugserver.py): the sampling CPU-profile
endpoint, exercised without the daemon tier (the live-daemon capture is
tests/test_operator_surface.py::test_debug_server_cpu_profile_from_live_daemon)
so the endpoint logic is covered in every environment — the module is
loaded straight from its file because `swarmkit_tpu.node`'s package
import pulls in the CA stack, which needs the `cryptography` wheel some
minimal environments lack."""
import importlib.util
import os
import threading
import time
import urllib.request

import swarmkit_tpu


def _load_debugserver():
    path = os.path.join(os.path.dirname(swarmkit_tpu.__file__),
                        "node", "debugserver.py")
    spec = importlib.util.spec_from_file_location("_dbgsrv_direct", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class _StubNode:
    node_id = "stub"
    addr = "127.0.0.1:0"
    is_leader = False


def test_profile_dump_sees_other_threads():
    profile_dump = _load_debugserver().profile_dump

    stop = threading.Event()

    def spin():
        while not stop.is_set():
            sum(range(200))

    t = threading.Thread(target=spin, name="spinner", daemon=True)
    t.start()
    try:
        out = profile_dump(0.3, interval=0.005)
    finally:
        stop.set()
        t.join()
    assert "CPU profile:" in out and "cumulative" in out
    assert "spin" in out, "sampler missed a busy thread"
    # the sampler must not profile itself
    assert "profile_dump" not in out.split("ncalls")[1]


def test_profile_endpoint_over_http():
    DebugServer = _load_debugserver().DebugServer

    srv = DebugServer("127.0.0.1:0", _StubNode())
    srv.start()
    try:
        base = f"http://{srv.addr}"
        out = urllib.request.urlopen(
            f"{base}/debug/profile?seconds=0.2").read().decode()
        assert "CPU profile:" in out and "cumulative" in out
        # seconds is clamped: a huge request must not wedge the handler
        t0 = time.monotonic()
        urllib.request.urlopen(f"{base}/debug/profile?seconds=0.05").read()
        assert time.monotonic() - t0 < 5
    finally:
        srv.stop()
