"""Control API and Watch API tests (reference behaviors:
manager/controlapi/*_test.go, manager/watchapi/watch_test.go)."""
import pytest

from swarmkit_tpu.api.objects import Cluster, Node, Service, Task
from swarmkit_tpu.api.specs import (
    Annotations,
    ClusterSpec,
    ConfigSpec,
    ContainerSpec,
    NetworkSpec,
    NodeSpec,
    PortConfig,
    SecretReference,
    SecretSpec,
    ServiceSpec,
    VolumeSpec,
)
from swarmkit_tpu.api.types import NodeRole, ServiceMode, TaskState
from swarmkit_tpu.controlapi import (
    AlreadyExists,
    ControlAPI,
    FailedPrecondition,
    InvalidArgument,
    ListFilters,
    NotFound,
)
from swarmkit_tpu.store.memory import MemoryStore
from swarmkit_tpu.watchapi import WatchAPI, WatchSelector


@pytest.fixture
def api():
    return ControlAPI(MemoryStore())


def spec(name="web", **kw):
    s = ServiceSpec(annotations=Annotations(name=name), **kw)
    return s


def test_create_get_update_remove_service(api):
    svc = api.create_service(spec())
    assert api.get_service(svc.id).spec.annotations.name == "web"

    # stale version is rejected
    new_spec = spec()
    new_spec.replicas = 5
    got = api.get_service(svc.id)
    updated = api.update_service(svc.id, got.meta.version, new_spec)
    assert updated.spec.replicas == 5
    assert updated.previous_spec is not None
    with pytest.raises(FailedPrecondition):
        api.update_service(svc.id, got.meta.version, new_spec)

    # rollback restores the previous spec
    cur = api.get_service(svc.id)
    rolled = api.update_service(svc.id, cur.meta.version, new_spec,
                                rollback=True)
    assert rolled.spec.replicas == 1

    api.remove_service(svc.id)
    with pytest.raises(NotFound):
        api.get_service(svc.id)


def test_service_validation(api):
    with pytest.raises(InvalidArgument):
        api.create_service(spec(name=""))
    with pytest.raises(InvalidArgument):
        api.create_service(spec(name="-bad-"))
    bad = spec()
    bad.task.placement.constraints = ["node.labels.x ~ y"]
    with pytest.raises(InvalidArgument):
        api.create_service(bad)
    badport = spec(name="p")
    badport.endpoint.ports = [PortConfig(protocol="icmp", target_port=80)]
    with pytest.raises(InvalidArgument):
        api.create_service(badport)
    # duplicate name
    api.create_service(spec(name="dup"))
    with pytest.raises(AlreadyExists):
        api.create_service(spec(name="dup"))
    # missing secret reference
    withsec = spec(name="s1")
    withsec.task.runtime = ContainerSpec(image="img")
    withsec.task.runtime.secrets = [SecretReference(secret_id="nope")]
    with pytest.raises(InvalidArgument):
        api.create_service(withsec)
    # rename forbidden
    svc = api.create_service(spec(name="fixed"))
    renamed = spec(name="other")
    with pytest.raises(InvalidArgument):
        api.update_service(svc.id, api.get_service(svc.id).meta.version,
                           renamed)


def test_secret_lifecycle(api):
    sec = api.create_secret(SecretSpec(
        annotations=Annotations(name="tls-key"), data=b"shh"))
    # read path strips data
    assert api.get_secret(sec.id).spec.data == b""
    assert api.list_secrets()[0].spec.data == b""

    # only labels may change
    s2 = SecretSpec(annotations=Annotations(name="tls-key",
                                            labels={"a": "1"}), data=b"")
    cur = api.store.view().get_secret(sec.id)
    api.update_secret(sec.id, cur.meta.version, s2)
    assert api.store.view().get_secret(sec.id).spec.annotations.labels == \
        {"a": "1"}
    # data survives label-only update
    assert api.store.view().get_secret(sec.id).spec.data == b"shh"

    # removal blocked while referenced
    s = spec(name="user")
    s.task.runtime = ContainerSpec(image="img")
    s.task.runtime.secrets = [SecretReference(secret_id=sec.id)]
    svc = api.create_service(s)
    with pytest.raises(InvalidArgument):
        api.remove_secret(sec.id)
    api.remove_service(svc.id)
    api.remove_secret(sec.id)
    with pytest.raises(NotFound):
        api.get_secret(sec.id)

    with pytest.raises(InvalidArgument):
        api.create_secret(SecretSpec(annotations=Annotations(name="big"),
                                     data=b"x" * (500 * 1024 + 1)))


def test_config_and_network(api):
    cfg = api.create_config(ConfigSpec(
        annotations=Annotations(name="nginx-conf"), data=b"server {}"))
    assert api.get_config(cfg.id).spec.data == b"server {}"

    net = api.create_network(NetworkSpec(annotations=Annotations(name="back")))
    s = spec(name="api")
    s.networks = []
    s.task.networks = []
    from swarmkit_tpu.api.specs import NetworkAttachmentConfig
    s.task.networks.append(NetworkAttachmentConfig(target=net.id))
    svc = api.create_service(s)
    with pytest.raises(FailedPrecondition):
        api.remove_network(net.id)
    api.remove_service(svc.id)
    api.remove_network(net.id)
    # only one ingress network allowed
    api.create_network(NetworkSpec(annotations=Annotations(name="ing1"),
                                   ingress=True))
    with pytest.raises(AlreadyExists):
        api.create_network(NetworkSpec(annotations=Annotations(name="ing2"),
                                       ingress=True))
    # operator subnets too small (or malformed) are rejected at the API,
    # not deferred to a background allocator warning
    for bad in ("10.5.0.0/31", "10.5.0.1/32", "garbage"):
        with pytest.raises(InvalidArgument):
            api.create_network(NetworkSpec(
                annotations=Annotations(name="tiny"),
                ipam={"subnet": bad}))


def test_node_update_and_remove(api):
    store = api.store
    n1 = Node(id="n1", spec=NodeSpec(annotations=Annotations(name="n1"),
                                     desired_role=NodeRole.MANAGER))
    n2 = Node(id="n2", spec=NodeSpec(annotations=Annotations(name="n2")))
    store.update(lambda tx: (tx.create(n1), tx.create(n2)))

    # demoting the only manager is refused
    demote = NodeSpec(annotations=Annotations(name="n1"),
                      desired_role=NodeRole.WORKER)
    with pytest.raises(FailedPrecondition):
        api.update_node("n1", api.get_node("n1").meta.version, demote)

    # promote n2, then demote n1 works
    promote = NodeSpec(annotations=Annotations(name="n2"),
                       desired_role=NodeRole.MANAGER)
    api.update_node("n2", api.get_node("n2").meta.version, promote)
    api.update_node("n1", api.get_node("n1").meta.version, demote)

    # managers can't be removed
    with pytest.raises(FailedPrecondition):
        api.remove_node("n2")
    api.remove_node("n1")
    with pytest.raises(NotFound):
        api.get_node("n1")


def test_cluster_token_rotation(api):
    """Rotation mints digest-pinned tokens on the replicated RootCAObj —
    the exact fields the CA validates joins against
    (controlapi cluster.go UpdateCluster; ca/server _role_from_token)."""
    from swarmkit_tpu.api.objects import RootCAObj
    from swarmkit_tpu.ca import RootCA
    from swarmkit_tpu.ca.config import generate_join_token, parse_join_token

    root = RootCA.create()
    c = Cluster(id="c1", spec=ClusterSpec(annotations=Annotations(name="default")))
    c.root_ca = RootCAObj(
        ca_cert_pem=root.cert_pem,
        ca_key_pem=root.key_pem or b"",
        cert_digest=root.digest(),
        join_token_worker=generate_join_token(root),
        join_token_manager=generate_join_token(root),
    )
    api.store.update(lambda tx: tx.create(c))
    got = api.get_cluster("c1")
    t1 = got.root_ca.join_token_worker
    m1 = got.root_ca.join_token_manager

    out = api.update_cluster("c1", got.meta.version, got.spec,
                             rotate_worker_token=True)
    assert out.root_ca.join_token_worker != t1
    assert out.root_ca.join_token_worker.startswith("SWMTKN-1-")
    # the new token pins THIS cluster's root digest (joins must validate)
    assert parse_join_token(
        out.root_ca.join_token_worker).root_digest == root.digest()
    # manager token untouched without its rotation flag
    assert out.root_ca.join_token_manager == m1

    # unlock-key rotation replaces the replicated KEK; reads redact it —
    # get_unlock_key is the sanctioned path
    out2 = api.update_cluster("c1", out.meta.version, out.spec,
                              rotate_unlock_key=True)
    assert out2.unlock_keys == []          # redacted on the wire
    key = api.get_unlock_key("c1")
    assert key
    # the stored cluster actually carries it (server-side view)
    raw = api.store.view().get_cluster("c1")
    assert raw.unlock_keys and raw.unlock_keys[0].decode() == key
    # CA signing material never leaves the control surface either
    assert out2.root_ca.ca_key_pem == b""


def test_list_filters(api):
    api.create_service(spec(name="web-1"))
    api.create_service(spec(name="web-2"))
    s3 = spec(name="db", mode=ServiceMode.GLOBAL)
    api.create_service(s3)
    assert len(api.list_services()) == 3
    assert len(api.list_services(ListFilters(name_prefixes=["web-"]))) == 2
    assert len(api.list_services(ListFilters(names=["db"]))) == 1
    assert len(api.list_services(
        ListFilters(modes=[ServiceMode.GLOBAL]))) == 1


def test_volume_lifecycle(api):
    v = api.create_volume(VolumeSpec(annotations=Annotations(name="vol1"),
                                     driver="csi.example"))
    with pytest.raises(InvalidArgument):
        api.create_volume(VolumeSpec(annotations=Annotations(name="vol2")))
    # in-use volume can't be removed without force
    t = Task(id="t1", volumes=[v.id])
    t.status.state = TaskState.RUNNING
    api.store.update(lambda tx: tx.create(t))
    with pytest.raises(FailedPrecondition):
        api.remove_volume(v.id)
    api.remove_volume(v.id, force=True)
    assert api.get_volume(v.id).pending_delete


def test_extension_resource(api):
    ext = api.create_extension(Annotations(name="widget"))
    res = api.create_resource(Annotations(name="w1"), "widget", b"payload")
    with pytest.raises(FailedPrecondition):
        api.remove_extension(ext.id)
    with pytest.raises(InvalidArgument):
        api.create_resource(Annotations(name="w2"), "nope")
    assert len(api.list_resources(kind="widget")) == 1
    api.remove_resource(res.id)
    api.remove_extension(ext.id)


def test_watchapi_filtered_stream(api):
    w = WatchAPI(api.store)
    ch = w.watch([WatchSelector(kind="service", name_prefix="web")])
    api.create_service(spec(name="web-1"))
    api.create_service(spec(name="db"))
    ev = ch.get(timeout=2)
    assert ev.obj.spec.annotations.name == "web-1"
    # db event filtered out; next event would be an update to web-1
    svc = api.list_services(ListFilters(names=["web-1"]))[0]
    ns = spec(name="web-1")
    ns.replicas = 9
    api.update_service(svc.id, svc.meta.version, ns)
    ev2 = ch.get(timeout=2)
    assert ev2.obj.spec.replicas == 9
    ch.close()


def test_watchapi_resume_replay():
    """watch_from replays history through a history-retaining proposer."""
    from swarmkit_tpu.raft.proposer import RaftProposer
    from swarmkit_tpu.raft.testutils import RaftCluster

    c = RaftCluster(1)
    node = c.nodes[1]
    prop = RaftProposer(node)
    store = MemoryStore(proposer=prop)
    prop.attach_store(store)
    leader = c.tick_until_leader()
    assert leader.id == 1

    api = ControlAPI(store)

    def propose(fn):
        import threading
        import time
        t = threading.Thread(target=fn)
        t.start()
        deadline = time.time() + 10
        while t.is_alive() and time.time() < deadline:
            c.settle()
        t.join(timeout=5)

    propose(lambda: api.create_service(spec(name="a")))
    v = store.version.index
    propose(lambda: api.create_service(spec(name="b")))
    w = WatchAPI(store)
    ch = w.watch([WatchSelector(kind="service")], resume_from=v)
    ev = ch.get(timeout=2)
    assert ev.obj.spec.annotations.name == "b"
    ch.close()
