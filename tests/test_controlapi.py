"""Control API and Watch API tests (reference behaviors:
manager/controlapi/*_test.go, manager/watchapi/watch_test.go)."""
import pytest

from swarmkit_tpu.api.objects import Cluster, Node, Service, Task
from swarmkit_tpu.api.specs import (
    Annotations,
    ClusterSpec,
    ConfigSpec,
    ContainerSpec,
    NetworkSpec,
    NodeSpec,
    PortConfig,
    SecretReference,
    SecretSpec,
    ServiceSpec,
    VolumeSpec,
)
from swarmkit_tpu.api.types import NodeRole, ServiceMode, TaskState
from swarmkit_tpu.controlapi import (
    AlreadyExists,
    ControlAPI,
    FailedPrecondition,
    InvalidArgument,
    ListFilters,
    NotFound,
)
from swarmkit_tpu.store.memory import MemoryStore
from swarmkit_tpu.watchapi import WatchAPI, WatchSelector


@pytest.fixture
def api():
    return ControlAPI(MemoryStore())


def spec(name="web", **kw):
    s = ServiceSpec(annotations=Annotations(name=name), **kw)
    return s


def test_create_get_update_remove_service(api):
    svc = api.create_service(spec())
    assert api.get_service(svc.id).spec.annotations.name == "web"

    # stale version is rejected
    new_spec = spec()
    new_spec.replicas = 5
    got = api.get_service(svc.id)
    updated = api.update_service(svc.id, got.meta.version, new_spec)
    assert updated.spec.replicas == 5
    assert updated.previous_spec is not None
    with pytest.raises(FailedPrecondition):
        api.update_service(svc.id, got.meta.version, new_spec)

    # rollback restores the previous spec
    cur = api.get_service(svc.id)
    rolled = api.update_service(svc.id, cur.meta.version, new_spec,
                                rollback=True)
    assert rolled.spec.replicas == 1

    api.remove_service(svc.id)
    with pytest.raises(NotFound):
        api.get_service(svc.id)


def test_service_validation(api):
    with pytest.raises(InvalidArgument):
        api.create_service(spec(name=""))
    with pytest.raises(InvalidArgument):
        api.create_service(spec(name="-bad-"))
    bad = spec()
    bad.task.placement.constraints = ["node.labels.x ~ y"]
    with pytest.raises(InvalidArgument):
        api.create_service(bad)
    badport = spec(name="p")
    badport.endpoint.ports = [PortConfig(protocol="icmp", target_port=80)]
    with pytest.raises(InvalidArgument):
        api.create_service(badport)
    # duplicate name
    api.create_service(spec(name="dup"))
    with pytest.raises(AlreadyExists):
        api.create_service(spec(name="dup"))
    # missing secret reference
    withsec = spec(name="s1")
    withsec.task.runtime = ContainerSpec(image="img")
    withsec.task.runtime.secrets = [SecretReference(secret_id="nope")]
    with pytest.raises(InvalidArgument):
        api.create_service(withsec)
    # rename forbidden
    svc = api.create_service(spec(name="fixed"))
    renamed = spec(name="other")
    with pytest.raises(InvalidArgument):
        api.update_service(svc.id, api.get_service(svc.id).meta.version,
                           renamed)


def test_secret_lifecycle(api):
    sec = api.create_secret(SecretSpec(
        annotations=Annotations(name="tls-key"), data=b"shh"))
    # read path strips data
    assert api.get_secret(sec.id).spec.data == b""
    assert api.list_secrets()[0].spec.data == b""

    # only labels may change
    s2 = SecretSpec(annotations=Annotations(name="tls-key",
                                            labels={"a": "1"}), data=b"")
    cur = api.store.view().get_secret(sec.id)
    api.update_secret(sec.id, cur.meta.version, s2)
    assert api.store.view().get_secret(sec.id).spec.annotations.labels == \
        {"a": "1"}
    # data survives label-only update
    assert api.store.view().get_secret(sec.id).spec.data == b"shh"

    # removal blocked while referenced
    s = spec(name="user")
    s.task.runtime = ContainerSpec(image="img")
    s.task.runtime.secrets = [SecretReference(
        secret_id=sec.id, secret_name="tls-key", target="key.pem")]
    svc = api.create_service(s)
    with pytest.raises(InvalidArgument):
        api.remove_secret(sec.id)
    api.remove_service(svc.id)
    api.remove_secret(sec.id)
    with pytest.raises(NotFound):
        api.get_secret(sec.id)

    with pytest.raises(InvalidArgument):
        api.create_secret(SecretSpec(annotations=Annotations(name="big"),
                                     data=b"x" * (500 * 1024 + 1)))


def test_config_and_network(api):
    cfg = api.create_config(ConfigSpec(
        annotations=Annotations(name="nginx-conf"), data=b"server {}"))
    assert api.get_config(cfg.id).spec.data == b"server {}"

    net = api.create_network(NetworkSpec(annotations=Annotations(name="back")))
    s = spec(name="api")
    s.networks = []
    s.task.networks = []
    from swarmkit_tpu.api.specs import NetworkAttachmentConfig
    s.task.networks.append(NetworkAttachmentConfig(target=net.id))
    svc = api.create_service(s)
    with pytest.raises(FailedPrecondition):
        api.remove_network(net.id)
    api.remove_service(svc.id)
    api.remove_network(net.id)
    # only one ingress network allowed
    api.create_network(NetworkSpec(annotations=Annotations(name="ing1"),
                                   ingress=True))
    with pytest.raises(AlreadyExists):
        api.create_network(NetworkSpec(annotations=Annotations(name="ing2"),
                                       ingress=True))
    # operator subnets too small (or malformed) are rejected at the API,
    # not deferred to a background allocator warning
    for bad in ("10.5.0.0/31", "10.5.0.1/32", "garbage"):
        with pytest.raises(InvalidArgument):
            api.create_network(NetworkSpec(
                annotations=Annotations(name="tiny"),
                ipam={"subnet": bad}))


def test_node_update_and_remove(api):
    store = api.store
    n1 = Node(id="n1", spec=NodeSpec(annotations=Annotations(name="n1"),
                                     desired_role=NodeRole.MANAGER))
    n2 = Node(id="n2", spec=NodeSpec(annotations=Annotations(name="n2")))
    store.update(lambda tx: (tx.create(n1), tx.create(n2)))

    # demoting the only manager is refused
    demote = NodeSpec(annotations=Annotations(name="n1"),
                      desired_role=NodeRole.WORKER)
    with pytest.raises(FailedPrecondition):
        api.update_node("n1", api.get_node("n1").meta.version, demote)

    # promote n2, then demote n1 works
    promote = NodeSpec(annotations=Annotations(name="n2"),
                       desired_role=NodeRole.MANAGER)
    api.update_node("n2", api.get_node("n2").meta.version, promote)
    api.update_node("n1", api.get_node("n1").meta.version, demote)

    # managers can't be removed
    with pytest.raises(FailedPrecondition):
        api.remove_node("n2")
    api.remove_node("n1")
    with pytest.raises(NotFound):
        api.get_node("n1")


def test_cluster_token_rotation(api):
    """Rotation mints digest-pinned tokens on the replicated RootCAObj —
    the exact fields the CA validates joins against
    (controlapi cluster.go UpdateCluster; ca/server _role_from_token)."""
    from swarmkit_tpu.api.objects import RootCAObj
    from swarmkit_tpu.ca import RootCA
    from swarmkit_tpu.ca.config import generate_join_token, parse_join_token

    root = RootCA.create()
    c = Cluster(id="c1", spec=ClusterSpec(annotations=Annotations(name="default")))
    c.root_ca = RootCAObj(
        ca_cert_pem=root.cert_pem,
        ca_key_pem=root.key_pem or b"",
        cert_digest=root.digest(),
        join_token_worker=generate_join_token(root),
        join_token_manager=generate_join_token(root),
    )
    api.store.update(lambda tx: tx.create(c))
    got = api.get_cluster("c1")
    t1 = got.root_ca.join_token_worker
    m1 = got.root_ca.join_token_manager

    out = api.update_cluster("c1", got.meta.version, got.spec,
                             rotate_worker_token=True)
    assert out.root_ca.join_token_worker != t1
    assert out.root_ca.join_token_worker.startswith("SWMTKN-1-")
    # the new token pins THIS cluster's root digest (joins must validate)
    assert parse_join_token(
        out.root_ca.join_token_worker).root_digest == root.digest()
    # manager token untouched without its rotation flag
    assert out.root_ca.join_token_manager == m1

    # unlock-key rotation replaces the replicated KEK; reads redact it —
    # get_unlock_key is the sanctioned path
    out2 = api.update_cluster("c1", out.meta.version, out.spec,
                              rotate_unlock_key=True)
    assert out2.unlock_keys == []          # redacted on the wire
    key = api.get_unlock_key("c1")
    assert key
    # the stored cluster actually carries it (server-side view)
    raw = api.store.view().get_cluster("c1")
    assert raw.unlock_keys and raw.unlock_keys[0].decode() == key
    # CA signing material never leaves the control surface either
    assert out2.root_ca.ca_key_pem == b""


def test_list_filters(api):
    api.create_service(spec(name="web-1"))
    api.create_service(spec(name="web-2"))
    s3 = spec(name="db", mode=ServiceMode.GLOBAL)
    api.create_service(s3)
    assert len(api.list_services()) == 3
    assert len(api.list_services(ListFilters(name_prefixes=["web-"]))) == 2
    assert len(api.list_services(ListFilters(names=["db"]))) == 1
    assert len(api.list_services(
        ListFilters(modes=[ServiceMode.GLOBAL]))) == 1


def test_volume_lifecycle(api):
    v = api.create_volume(VolumeSpec(annotations=Annotations(name="vol1"),
                                     driver="csi.example"))
    with pytest.raises(InvalidArgument):
        api.create_volume(VolumeSpec(annotations=Annotations(name="vol2")))
    # in-use volume can't be removed without force
    t = Task(id="t1", volumes=[v.id])
    t.status.state = TaskState.RUNNING
    api.store.update(lambda tx: tx.create(t))
    with pytest.raises(FailedPrecondition):
        api.remove_volume(v.id)
    api.remove_volume(v.id, force=True)
    assert api.get_volume(v.id).pending_delete


def test_extension_resource(api):
    ext = api.create_extension(Annotations(name="widget"))
    res = api.create_resource(Annotations(name="w1"), "widget", b"payload")
    with pytest.raises(FailedPrecondition):
        api.remove_extension(ext.id)
    with pytest.raises(InvalidArgument):
        api.create_resource(Annotations(name="w2"), "nope")
    assert len(api.list_resources(kind="widget")) == 1
    api.remove_resource(res.id)
    api.remove_extension(ext.id)


def test_watchapi_filtered_stream(api):
    w = WatchAPI(api.store)
    ch = w.watch([WatchSelector(kind="service", name_prefix="web")])
    api.create_service(spec(name="web-1"))
    api.create_service(spec(name="db"))
    ev = ch.get(timeout=2)
    assert ev.obj.spec.annotations.name == "web-1"
    # db event filtered out; next event would be an update to web-1
    svc = api.list_services(ListFilters(names=["web-1"]))[0]
    ns = spec(name="web-1")
    ns.replicas = 9
    api.update_service(svc.id, svc.meta.version, ns)
    ev2 = ch.get(timeout=2)
    assert ev2.obj.spec.replicas == 9
    ch.close()


def test_watchapi_resume_replay():
    """watch_from replays history through a history-retaining proposer."""
    from swarmkit_tpu.raft.proposer import RaftProposer
    from swarmkit_tpu.raft.testutils import RaftCluster

    c = RaftCluster(1)
    node = c.nodes[1]
    prop = RaftProposer(node)
    store = MemoryStore(proposer=prop)
    prop.attach_store(store)
    leader = c.tick_until_leader()
    assert leader.id == 1

    api = ControlAPI(store)

    def propose(fn):
        import threading
        import time
        t = threading.Thread(target=fn)
        t.start()
        deadline = time.time() + 10
        while t.is_alive() and time.time() < deadline:
            c.settle()
        t.join(timeout=5)

    propose(lambda: api.create_service(spec(name="a")))
    v = store.version.index
    propose(lambda: api.create_service(spec(name="b")))
    w = WatchAPI(store)
    ch = w.watch([WatchSelector(kind="service")], resume_from=v)
    ev = ch.get(timeout=2)
    assert ev.obj.spec.annotations.name == "b"
    ch.close()


# --------------------------------------------------------------------------
# Service-spec validation catalogue (table-driven, mirroring the case
# structure of reference manager/controlapi/service_test.go:
# TestValidateResources / RestartPolicy / Update / EndpointSpec /
# SecretRefs / ConfigRefs / Mounts / Mode / Job / checkPortConflicts).
# --------------------------------------------------------------------------

def _base_spec(name="vsvc"):
    from swarmkit_tpu.api.specs import TaskSpec

    s = ServiceSpec(annotations=Annotations(name=name),
                    task=TaskSpec(runtime=ContainerSpec(command=["true"])))
    return s


def _bad_specs():
    from swarmkit_tpu.api.specs import (
        ConfigReference,
        JobSpec,
        NetworkAttachmentConfig,
        UpdateConfig,
        VolumeMount,
    )
    from swarmkit_tpu.api.types import RestartCondition

    def case(desc, msg, fn):
        def build():
            s = _base_spec()
            fn(s)
            return s
        return pytest.param(build, msg, id=desc)

    def set_(path, value):
        def fn(s):
            obj = s
            *head, last = path.split(".")
            for part in head:
                obj = getattr(obj, part)
            setattr(obj, last, value)
        return fn

    def job(fn_extra=None):
        def fn(s):
            s.mode = ServiceMode.REPLICATED_JOB
            s.job = JobSpec(max_concurrent=1, total_completions=1)
            s.task.restart.condition = RestartCondition.NONE
            if fn_extra:
                fn_extra(s)
        return fn

    return [
        # ---- resources (validateResources) ----
        case("cpu-below-quantum", "invalid cpu",
             set_("task.resources.reservations.nano_cpus", 1000)),
        case("mem-below-4mib", "invalid memory",
             set_("task.resources.reservations.memory_bytes", 1 << 20)),
        case("limits-cpu-below-quantum", "invalid cpu",
             set_("task.resources.limits.nano_cpus", 5)),
        case("negative-generic", "non-negative",
             lambda s: s.task.resources.reservations.generic.update(
                 {"gpu": -1})),
        # ---- restart policy ----
        case("restart-delay-negative", "restart-delay",
             set_("task.restart.delay", -1.0)),
        case("restart-window-negative", "restart-window",
             set_("task.restart.window", -0.5)),
        case("restart-attempts-negative", "restart-max-attempts",
             set_("task.restart.max_attempts", -2)),
        # ---- update / rollback config ----
        case("update-delay-negative", "update-delay",
             set_("update.delay", -1.0)),
        case("update-monitor-negative", "update-monitor",
             set_("update.monitor", -1.0)),
        case("update-ratio-negative", "maxfailureratio",
             set_("update.max_failure_ratio", -0.1)),
        case("update-ratio-above-1", "maxfailureratio",
             set_("update.max_failure_ratio", 1.5)),
        case("update-parallelism-negative", "parallelism",
             set_("update.parallelism", -1)),
        case("rollback-delay-negative", "rollback-delay",
             lambda s: setattr(s, "rollback", UpdateConfig(delay=-3.0))),
        # ---- endpoint spec ----
        case("dnsrr-with-ingress-port", "dnsrr", lambda s: (
            setattr(s.endpoint, "mode", "dnsrr"),
            s.endpoint.ports.append(PortConfig(
                protocol="tcp", target_port=80, published_port=8080,
                publish_mode="ingress")))),
        case("duplicate-published-ports", "duplicate", lambda s: (
            s.endpoint.ports.extend([
                PortConfig(protocol="tcp", target_port=80,
                           published_port=8080),
                PortConfig(protocol="tcp", target_port=81,
                           published_port=8080)]))),
        case("bad-publish-mode", "publish mode", lambda s: (
            s.endpoint.ports.append(PortConfig(
                protocol="tcp", target_port=80, publish_mode="weird")))),
        case("missing-target-port", "target_port", lambda s: (
            s.endpoint.ports.append(PortConfig(protocol="tcp")))),
        case("bad-protocol", "protocol", lambda s: (
            s.endpoint.ports.append(PortConfig(protocol="icmp",
                                               target_port=80)))),
        # ---- secret / config refs ----
        case("secret-ref-no-id", "malformed secret", lambda s: (
            s.task.runtime.secrets.append(SecretReference(
                secret_name="x", target="f")))),
        case("secret-ref-no-name", "malformed secret", lambda s: (
            s.task.runtime.secrets.append(SecretReference(
                secret_id="sid", target="f")))),
        case("secret-ref-no-target", "no target", lambda s: (
            s.task.runtime.secrets.append(SecretReference(
                secret_id="sid", secret_name="x")))),
        case("secret-refs-conflicting-target", "conflicting", lambda s: (
            s.task.runtime.secrets.extend([
                SecretReference(secret_id="a", secret_name="na", target="f"),
                SecretReference(secret_id="b", secret_name="nb",
                                target="f")]))),
        case("secret-ref-nonexistent", "not found", lambda s: (
            s.task.runtime.secrets.append(SecretReference(
                secret_id="ghost", secret_name="g", target="f")))),
        case("config-ref-no-id", "malformed config", lambda s: (
            s.task.runtime.configs.append(ConfigReference(
                config_name="x", target="f")))),
        case("config-refs-conflicting-target", "conflicting", lambda s: (
            s.task.runtime.configs.extend([
                ConfigReference(config_id="a", config_name="na", target="f"),
                ConfigReference(config_id="b", config_name="nb",
                                target="f")]))),
        # ---- mounts ----
        case("mount-no-target", "mount target", lambda s: (
            s.task.runtime.mounts.append(VolumeMount(source="v")))),
        case("mount-relative-target", "absolute", lambda s: (
            s.task.runtime.mounts.append(VolumeMount(source="v",
                                                     target="rel/path")))),
        # ---- mode / job ----
        case("negative-replicas", "non-negative",
             set_("replicas", -1)),
        case("negative-max-replicas", "max-replicas",
             set_("task.placement.max_replicas", -1)),
        case("job-negative-concurrent", "concurrent",
             job(lambda s: setattr(s.job, "max_concurrent", -1))),
        case("job-negative-completions", "not be negative",
             job(lambda s: setattr(s.job, "total_completions", -1))),
        case("job-with-update-config", "update config",
             job(lambda s: setattr(s.update, "parallelism", 7))),
        case("job-restart-any", "restart",
             job(lambda s: setattr(s.task.restart, "condition",
                                   __import__("swarmkit_tpu.api.types",
                                              fromlist=["RestartCondition"])
                                   .RestartCondition.ANY))),
        # ---- constraints / networks ----
        case("bad-constraint", "constraint",
             lambda s: s.task.placement.constraints.append("node.labels =")),
        case("nonexistent-network", "not found", lambda s: (
            s.task.networks.append(NetworkAttachmentConfig(
                target="no-such-net")))),
    ]


@pytest.mark.parametrize("build,msg", _bad_specs())
def test_create_service_rejects_invalid_spec(api, build, msg):
    with pytest.raises(InvalidArgument) as exc:
        api.create_service(build())
    assert msg.lower() in str(exc.value).lower(), str(exc.value)
    # nothing was created
    assert api.list_services() == []


def test_valid_spec_boundaries_accepted(api):
    """The catalogue must not over-reject: boundary values are legal."""
    s = _base_spec("boundary")
    s.task.resources.reservations.nano_cpus = 1_000_000        # exactly min
    s.task.resources.reservations.memory_bytes = 4 * 1024 * 1024
    s.update.max_failure_ratio = 1.0
    s.endpoint.ports.extend([
        PortConfig(protocol="tcp", target_port=80, published_port=8080),
        PortConfig(protocol="udp", target_port=80, published_port=8080),
    ])  # same port, different protocol: legal
    api.create_service(s)


def test_ingress_network_attachment_rejected(api):
    from swarmkit_tpu.api.specs import NetworkAttachmentConfig

    ing = api.create_network(NetworkSpec(
        annotations=Annotations(name="ingress"), ingress=True))
    s = _base_spec("wants-ingress")
    s.task.networks.append(NetworkAttachmentConfig(target=ing.id))
    with pytest.raises(InvalidArgument) as exc:
        api.create_service(s)
    assert "ingress" in str(exc.value)


def test_port_conflict_matrix(api):
    """service.go checkPortConflicts: ingress ports are cluster-unique;
    host ports may collide with each other but not with ingress."""
    def with_port(name, mode, port=8088):
        s = _base_spec(name)
        s.endpoint.ports.append(PortConfig(
            protocol="tcp", target_port=80, published_port=port,
            publish_mode=mode))
        return s

    a = api.create_service(with_port("ing-a", "ingress"))
    with pytest.raises(InvalidArgument) as exc:
        api.create_service(with_port("ing-b", "ingress"))
    assert "already in use" in str(exc.value)
    with pytest.raises(InvalidArgument):
        api.create_service(with_port("host-b", "host"))

    # distinct port is fine; host+host sharing is fine
    api.create_service(with_port("host-c", "host", port=8090))
    api.create_service(with_port("host-d", "host", port=8090))
    # ...but ingress over an existing host port is not
    with pytest.raises(InvalidArgument):
        api.create_service(with_port("ing-e", "ingress", port=8090))

    # updating the SAME service keeps its own ports without conflicting
    got = api.get_service(a.id)
    new = with_port("ing-a", "ingress")
    new.replicas = 2
    api.update_service(a.id, got.meta.version, new)


def test_update_networks_alone_rejected(api):
    from swarmkit_tpu.api.specs import NetworkAttachmentConfig
    from swarmkit_tpu.controlapi import Unimplemented

    n1 = api.create_network(NetworkSpec(annotations=Annotations(name="n1")))
    n2 = api.create_network(NetworkSpec(annotations=Annotations(name="n2")))
    s = _base_spec("netsvc")
    s.networks.append(NetworkAttachmentConfig(target=n1.id))
    svc = api.create_service(s)

    got = api.get_service(svc.id)
    upd = _base_spec("netsvc")
    upd.networks.append(NetworkAttachmentConfig(target=n2.id))
    with pytest.raises(Unimplemented):
        api.update_service(svc.id, got.meta.version, upd)

    # migrating to task.networks in the same request is allowed
    upd2 = _base_spec("netsvc")
    upd2.networks.append(NetworkAttachmentConfig(target=n2.id))
    upd2.task.networks.append(NetworkAttachmentConfig(target=n2.id))
    api.update_service(svc.id, got.meta.version, upd2)


def test_dynamic_ingress_port_conflicts_at_create(api):
    """service.go:644-660: a dynamically assigned ingress port lives only
    on svc.endpoint — explicit publishers of that port must be rejected."""
    s = _base_spec("dyn")
    s.endpoint.ports.append(PortConfig(protocol="tcp", target_port=80,
                                       published_port=0,
                                       publish_mode="ingress"))
    svc = api.create_service(s)
    # simulate the allocator materializing the dynamic port 30000
    def alloc(tx):
        cur = tx.get_service(svc.id).copy()
        cur.endpoint = {"ports_allocated": True,
                        "ports": [("tcp", 80, 30000, "ingress")],
                        "virtual_ips": []}
        tx.update(cur)
    api.store.update(alloc)

    thief = _base_spec("thief")
    thief.endpoint.ports.append(PortConfig(protocol="tcp", target_port=81,
                                           published_port=30000,
                                           publish_mode="ingress"))
    with pytest.raises(InvalidArgument) as exc:
        api.create_service(thief)
    assert "already in use" in str(exc.value)


def test_update_endpoint_unchanged_skips_conflict_check(api):
    """Grandfathered pre-validation state must stay updatable as long as
    the endpoint spec is untouched (service.go:837 DeepEqual guard)."""
    def mk(name):
        s = _base_spec(name)
        s.endpoint.ports.append(PortConfig(protocol="tcp", target_port=80,
                                           published_port=9300,
                                           publish_mode="ingress"))
        return s

    # two conflicting services written straight to the store (no API)
    import swarmkit_tpu.api.objects as objs
    from swarmkit_tpu.api.objects import Version as V

    def seed(tx):
        for name in ("old-a", "old-b"):
            svc = objs.Service(id=f"legacy-{name}", spec=mk(name))
            svc.spec_version = V(1)
            tx.create(svc)
    api.store.update(seed)

    # scaling one of them (endpoint untouched) must work
    got = api.get_service("legacy-old-a")
    upd = mk("old-a")
    upd.replicas = 3
    api.update_service("legacy-old-a", got.meta.version, upd)
    # ...but changing its endpoint re-runs the conflict check
    got = api.get_service("legacy-old-a")
    upd2 = mk("old-a")
    upd2.endpoint.ports[0].published_port = 9300
    upd2.endpoint.ports.append(PortConfig(protocol="udp", target_port=80,
                                          published_port=9300,
                                          publish_mode="ingress"))
    with pytest.raises(InvalidArgument):
        api.update_service("legacy-old-a", got.meta.version, upd2)


def test_update_network_aliases_alone_rejected(api):
    """Full attachment configs compare (reference DeepEqual), not just
    targets: an aliases-only change to spec.networks must be refused."""
    from swarmkit_tpu.api.specs import NetworkAttachmentConfig
    from swarmkit_tpu.controlapi import Unimplemented

    n1 = api.create_network(NetworkSpec(annotations=Annotations(name="m1")))
    s = _base_spec("aliassvc")
    s.networks.append(NetworkAttachmentConfig(target=n1.id))
    svc = api.create_service(s)

    got = api.get_service(svc.id)
    upd = _base_spec("aliassvc")
    upd.networks.append(NetworkAttachmentConfig(target=n1.id,
                                                aliases=["new-alias"]))
    with pytest.raises(Unimplemented):
        api.update_service(svc.id, got.meta.version, upd)


def test_spec_fuzz_never_crashes_validation(api):
    """Validation robustness: randomized adversarial specs (exotic
    strings, wrong-typed-but-constructible values, boundary numbers,
    hostile constraints/ports/resources) must either be accepted or be
    rejected with a CONTROLLED ControlError — any other exception class
    escaping create_service is a server crash a malicious or buggy
    client could trigger at will."""
    import random

    from swarmkit_tpu.api.specs import (
        EndpointSpec, Placement, ResourceRequirements, Resources,
        RestartPolicy, TaskSpec, UpdateConfig)
    from swarmkit_tpu.controlapi import ControlError

    rng = random.Random(20260801)
    strings = ["", " ", "a" * 4096, "node.labels.x==", "==", "!=y",
               "node.role == manager", "node.ip != 10.0.0.0/8",
               "bad constraint \x00", "名前", "-leading", "UPPER",
               "has space", "dot.name", "a" * 63, "a" * 64, "💥",
               "{{.Node.ID}}", "$(rm -rf /)", "\n", "None", "web"]
    ints = [-2**31, -1, 0, 1, 3, 1 << 15, 30000, 32767, 65535, 65536,
            1 << 62]

    def maybe(v):
        return v if rng.random() < 0.7 else None

    accepted = rejected = 0
    for i in range(300):
        kw = {}
        if rng.random() < 0.8:
            kw["replicas"] = rng.choice(
                ints + [float("nan"), float("inf"), 2.5, "3", None])
        if rng.random() < 0.5:
            kw["mode"] = rng.choice(list(ServiceMode))
        task_kw = {}
        if rng.random() < 0.6:
            task_kw["runtime"] = ContainerSpec(
                image=maybe(rng.choice(strings)),
                command=rng.choice([None, [], [rng.choice(strings)]]),
                env=rng.choice([None, [f"{rng.choice(strings)}="
                                       f"{rng.choice(strings)}"]]))
        if rng.random() < 0.5:
            task_kw["placement"] = Placement(
                constraints=[rng.choice(strings + [None])
                             for _ in range(rng.randint(1, 3))],
                max_replicas=rng.choice(ints + ["x", 2.5]))
        if rng.random() < 0.4:
            task_kw["resources"] = ResourceRequirements(
                reservations=Resources(
                    nano_cpus=rng.choice(ints),
                    memory_bytes=rng.choice(ints),
                    generic=rng.choice([None, {}, {"gpu": -1},
                                        {"gpu": "four"}, [("gpu", 1)]])))
        if rng.random() < 0.3:
            task_kw["restart"] = RestartPolicy(
                condition=rng.randint(-1, 5),
                delay=rng.choice([-1.0, 0.0, 1e18]),
                max_attempts=rng.choice(ints))
        if rng.random() < 0.3:
            kw["update"] = UpdateConfig(
                parallelism=rng.choice(ints),
                delay=rng.choice([-5.0, 0.0, 1e9]),
                failure_action=rng.choice(
                    ["pause", "continue", "rollback", "explode", ""]))
        if rng.random() < 0.4:
            kw["endpoint"] = EndpointSpec(ports=rng.choice(
                [[None]] + [[PortConfig(target_port=rng.choice(ints),
                                        published_port=rng.choice(ints))]]))
        name = (f"ok-{i}" if rng.random() < 0.4
                else rng.choice(strings))
        s = ServiceSpec(
            annotations=Annotations(name=name,
                                    labels={rng.choice(strings):
                                            rng.choice(strings)}),
            task=TaskSpec(**task_kw) if task_kw else None,
            **kw)
        try:
            svc = api.create_service(s)
            accepted += 1
            # a spec good enough to create must also round-trip
            assert api.get_service(svc.id) is not None
            api.remove_service(svc.id)
        except ControlError:
            rejected += 1
        # any other exception propagates and fails the test: that's the
        # crash this fuzz exists to catch

    # the generator must actually produce both outcomes or the fuzz
    # got too easy/too hostile to mean anything
    assert accepted > 5, f"only {accepted} specs accepted"
    assert rejected > 50, f"only {rejected} specs rejected"
