"""Integration tier: full-stack cluster scenarios over real TCP + mTLS,
modeled on the reference's integration harness
(/root/reference/integration/cluster_test.go:26-36 testCluster with
AddManager/AddAgent/RemoveNode/SetNodeRole/Leader; scenarios
integration/integration_test.go:196-965).

Complements test_daemon.py (worker join, follower-write forwarding, leader
kill, manager state-dir rejoin, promote/demote) with the remaining verdict-7
scenarios: leader demotion, worker restart/rejoin, node removal →
reschedule, wrong-cert join rejection, and root rotation under live nodes.
"""
import time

import pytest

from swarmkit_tpu.agent.testutils import FakeExecutor
from swarmkit_tpu.api.specs import Annotations, ServiceSpec
from swarmkit_tpu.api.types import NodeRole, NodeStatusState, TaskState
from swarmkit_tpu.node.daemon import SwarmNode
from swarmkit_tpu.rpc.services import RemoteControl
from swarmkit_tpu.store import by

from test_scheduler import wait_for  # noqa: E402

pytestmark = pytest.mark.daemon


class Cluster:
    """In-process cluster harness (cluster_test.go testCluster)."""

    def __init__(self, tmp_path):
        self.base = tmp_path
        self.nodes: list[SwarmNode] = []
        self._seq = 0

    # ------------------------------------------------------------ membership
    def _spawn(self, name, **kw):
        node = SwarmNode(
            state_dir=str(self.base / name),
            executor=FakeExecutor({"*": {"run_forever": True}},
                                  hostname=name),
            heartbeat_period=0.5,
            tick_interval=0.05,
            manager_refresh_interval=0.5,
            **kw,
        )
        node.start()
        self.nodes.append(node)
        return node

    def add_manager(self, name=None):
        name = name or f"m{self._next()}"
        if not self.nodes:
            n = self._spawn(name, listen_addr="127.0.0.1:0")
            assert wait_for(lambda: n.is_leader, timeout=30)
            return n
        mtok, _ = self.tokens()
        return self._spawn(name, listen_addr="127.0.0.1:0",
                           join_addr=self.leader().addr, join_token=mtok)

    def add_agent(self, name=None):
        name = name or f"w{self._next()}"
        _, wtok = self.tokens()
        addrs = ",".join(m.addr for m in self.managers())
        return self._spawn(name, join_addr=addrs, join_token=wtok)

    def _next(self):
        self._seq += 1
        return self._seq

    # -------------------------------------------------------------- queries
    def managers(self):
        return [n for n in self.nodes if n.manager is not None]

    def leader(self) -> SwarmNode:
        assert wait_for(lambda: any(n.is_leader for n in self.nodes
                                    if n.manager is not None), timeout=30)
        return next(n for n in self.nodes if n.is_leader)

    def tokens(self):
        m = self.leader()

        def seeded():
            c = m.store.view(lambda tx: tx.get_cluster(m.manager.cluster_id))
            return c is not None and c.root_ca is not None

        assert wait_for(seeded, timeout=30)
        c = m.store.view(lambda tx: tx.get_cluster(m.manager.cluster_id))
        return c.root_ca.join_token_manager, c.root_ca.join_token_worker

    def control(self, node=None) -> RemoteControl:
        node = node or self.leader()
        return RemoteControl(node.addr, node.security)

    def running(self, service_id, node=None) -> list:
        node = node or self.leader()
        tasks = node.store.view(
            lambda tx: tx.find_tasks(by.ByServiceID(service_id)))
        return [t for t in tasks if t.status.state == TaskState.RUNNING]

    def set_node_role(self, node_id, role):
        ctl = self.control()
        try:
            for _ in range(30):
                n = ctl.get_node(node_id)
                n.spec.desired_role = role
                try:
                    ctl.update_node(n.id, n.meta.version, n.spec)
                    return
                except Exception as exc:
                    if "out of sequence" not in str(exc):
                        raise
                    time.sleep(0.1)
            raise AssertionError("could not update node role")
        finally:
            ctl.close()

    def stop_all(self):
        for n in reversed(self.nodes):
            try:
                n.stop()
            except Exception:
                pass


@pytest.fixture
def cluster(tmp_path):
    c = Cluster(tmp_path)
    yield c
    c.stop_all()


def _create_service(cluster, name, replicas):
    # window > one RPC call timeout (30 s): a starved host can stall an
    # election past a single propose, and a 30 s window gave exactly one
    # attempt — the retry existed but could never run
    ctl = cluster.control()
    try:
        svc = None
        end = time.monotonic() + 75
        while svc is None:
            try:
                svc = ctl.create_service(ServiceSpec(
                    annotations=Annotations(name=name), replicas=replicas))
            except Exception:
                # a timed-out create may still have committed: adopt it
                try:
                    hit = [s for s in ctl.list_services()
                           if s.spec.annotations.name == name]
                    if hit:
                        return hit[0]
                except Exception:
                    pass
                if time.monotonic() >= end:
                    raise
                time.sleep(0.5)
        return svc
    finally:
        ctl.close()


def test_leader_demotion_moves_leadership(cluster):
    """integration_test.go:383-514 — demoting the raft LEADER transfers
    leadership, shrinks the quorum safely, and the cluster keeps serving."""
    m1 = cluster.add_manager()
    m2 = cluster.add_manager()
    m3 = cluster.add_manager()
    managers = [m1, m2, m3]
    assert wait_for(
        lambda: all(len(m.raft.members) == 3 for m in managers), timeout=30)

    svc = _create_service(cluster, "before-demote", 4)
    assert wait_for(lambda: len(cluster.running(svc.id)) == 4, timeout=30)

    old_leader = cluster.leader()
    cluster.set_node_role(old_leader.node_id, NodeRole.WORKER)

    # leadership must land on one of the other two, quorum shrinks to 2.
    # generous windows: three in-process raft stacks churn elections when a
    # loaded CI machine starves their tick threads for seconds at a time
    others = [m for m in managers if m is not old_leader]
    assert wait_for(lambda: any(m.is_leader for m in others), timeout=120)
    assert wait_for(
        lambda: all(len(m.raft.members) == 2 for m in others), timeout=120)
    assert wait_for(lambda: old_leader.manager is None, timeout=120)

    # the demoted node keeps working as a worker; the cluster serves writes
    svc2 = _create_service(cluster, "after-demote", 3)
    assert wait_for(lambda: len(cluster.running(svc2.id)) == 3, timeout=30)


def test_worker_restart_rejoins_same_identity(cluster):
    """integration_test.go node rejoin: a worker restarted from its state
    dir comes back with the same node identity and its tasks reconverge."""
    cluster.add_manager()
    w1 = cluster.add_agent("w-rejoin")
    leader = cluster.leader()

    def worker_ready():
        n = leader.store.view(lambda tx: tx.get_node(w1.node_id))
        return n is not None and n.status.state == NodeStatusState.READY

    assert wait_for(worker_ready, timeout=20)
    svc = _create_service(cluster, "steady", 4)
    assert wait_for(lambda: len(cluster.running(svc.id)) == 4, timeout=30)

    node_id = w1.node_id
    w1.stop()
    cluster.nodes.remove(w1)

    # heartbeat expiry marks it DOWN; its tasks reschedule on the manager
    def down():
        n = leader.store.view(lambda tx: tx.get_node(node_id))
        return n is not None and n.status.state == NodeStatusState.DOWN

    assert wait_for(down, timeout=30)
    assert wait_for(lambda: len(cluster.running(svc.id)) == 4, timeout=60)

    # restart from the same state dir: same identity, no token needed
    w1b = cluster._spawn("w-rejoin")
    assert wait_for(lambda: w1b.node_id == node_id, timeout=20)
    assert wait_for(worker_ready, timeout=30)


def test_node_removal_reschedules_tasks(cluster):
    """remove a worker via the control plane: its tasks move elsewhere and
    the node object disappears (controlapi node.go RemoveNode)."""
    cluster.add_manager()
    w1 = cluster.add_agent()
    leader = cluster.leader()

    assert wait_for(lambda: leader.store.view(
        lambda tx: tx.get_node(w1.node_id)) is not None, timeout=20)
    svc = _create_service(cluster, "spread", 6)
    assert wait_for(lambda: len(cluster.running(svc.id)) == 6, timeout=30)

    w1.stop()
    cluster.nodes.remove(w1)

    def node_down():
        n = leader.store.view(lambda tx: tx.get_node(w1.node_id))
        return n is not None and n.status.state == NodeStatusState.DOWN

    assert wait_for(node_down, timeout=30)

    ctl = cluster.control()
    try:
        ctl.remove_node(w1.node_id, force=True)
    finally:
        ctl.close()

    assert wait_for(lambda: leader.store.view(
        lambda tx: tx.get_node(w1.node_id)) is None, timeout=20)
    # all replicas land on the remaining node
    def all_on_manager():
        running = cluster.running(svc.id)
        return (len(running) == 6
                and all(t.node_id == leader.node_id for t in running))

    assert wait_for(all_on_manager, timeout=60)


def test_wrong_cert_join_rejected(cluster, tmp_path):
    """integration_test.go wrong-cert join: an identity minted by a
    DIFFERENT cluster's CA cannot talk to this cluster — the mTLS handshake
    (pinned to this cluster's root) refuses it."""
    cluster.add_manager()
    leader = cluster.leader()

    # a second, unrelated cluster mints the foreign identity
    foreign = Cluster(tmp_path / "foreign")
    try:
        fm = foreign.add_manager("fm1")
        from swarmkit_tpu.rpc.client import RPCClient

        with pytest.raises(Exception) as exc_info:
            c = RPCClient(leader.addr, security=fm.security)
            try:
                c.call("health.check")
            finally:
                c.close()
        msg = str(exc_info.value).lower()
        assert any(s in msg for s in ("ssl", "certificate", "tls",
                                      "handshake", "connection")), msg

        # and the legitimate identity still works (retry-tolerant: a
        # loaded machine can starve the in-process TLS server past a
        # single call timeout)
        last_err = [None]

        def legit_ok():
            ctl = cluster.control()
            try:
                return ctl.list_services() == []
            except Exception as exc:
                last_err[0] = exc  # kept for triage: flake vs real bug
                return False
            finally:
                ctl.close()

        assert wait_for(legit_ok, timeout=90), \
            f"legitimate identity never worked; last error: {last_err[0]!r}"

    finally:
        foreign.stop_all()


def test_join_token_rotation(cluster):
    """controlapi cluster.go UpdateCluster token rotation: a rotated worker
    token admits new joiners (digest-pinned against the cluster root) and
    the pre-rotation token is rejected."""
    cluster.add_manager()
    leader = cluster.leader()
    _, old_wtok = cluster.tokens()

    ctl = cluster.control()
    try:
        new_wtok = None
        for _ in range(20):   # cluster object is written by background
            c = ctl.list_clusters()[0]   # components; retry on conflicts
            try:
                c = ctl.update_cluster(c.id, c.meta.version, c.spec,
                                       rotate_worker_token=True)
                new_wtok = c.root_ca.join_token_worker
                break
            except Exception as exc:
                if "out of sequence" not in str(exc):
                    raise
                time.sleep(0.1)
        assert new_wtok is not None
    finally:
        ctl.close()
    assert new_wtok != old_wtok and new_wtok.startswith("SWMTKN-")

    w_new = cluster._spawn("w-newtok", join_addr=leader.addr,
                           join_token=new_wtok)
    assert wait_for(lambda: leader.store.view(
        lambda tx: tx.get_node(w_new.node_id)) is not None, timeout=20)

    stale = SwarmNode(
        state_dir=str(cluster.base / "w-stale"),
        executor=FakeExecutor({"*": {"run_forever": True}},
                              hostname="w-stale"),
        join_addr=leader.addr, join_token=old_wtok,
        heartbeat_period=0.5)
    with pytest.raises(Exception) as exc_info:
        stale.start()
    assert "token" in str(exc_info.value).lower()


def test_root_rotation_under_live_nodes(cluster):
    """ca/reconciler.go root rotation with the cluster live: after rotation
    every node renews onto the new root and the data plane keeps working."""
    m1 = cluster.add_manager()
    w1 = cluster.add_agent()
    leader = cluster.leader()

    def worker_ready():
        n = leader.store.view(lambda tx: tx.get_node(w1.node_id))
        return n is not None and n.status.state == NodeStatusState.READY

    assert wait_for(worker_ready, timeout=40)
    svc = _create_service(cluster, "pre-rotate", 4)
    assert wait_for(lambda: len(cluster.running(svc.id)) == 4, timeout=60)

    old_root = m1.security.root_ca.cert_pem
    leader.manager.ca_server.rotate_root_ca()

    # both nodes' TLS identities renew onto the new root
    def renewed():
        new_root = leader.manager.ca_server.root.cert_pem
        return (new_root != old_root
                and m1.security.root_ca.cert_pem == new_root
                and w1.security.root_ca.cert_pem == new_root)

    # renewal chains: session-plane root update -> node re-CSR -> signer
    # pass -> credential swap, each on its own timer (1 s renewer cadence);
    # a machine starved 5-10x by CPU burners stretches every hop, and 120 s
    # was observed insufficient under 4 saturating processes (wait_for
    # returns early when healthy)
    assert wait_for(renewed, timeout=300)

    # the data plane survives rotation: scale the service up over the wire
    ctl = cluster.control()
    try:
        cur = ctl.get_service(svc.id)
        cur.spec.replicas = 6
        ctl.update_service(svc.id, cur.meta.version, cur.spec)
    finally:
        ctl.close()
    assert wait_for(lambda: len(cluster.running(svc.id)) == 6, timeout=60)


def test_ca_rotation_via_control_api(cluster):
    """VERDICT r04 item 4 done-criterion: root rotation driven PURELY
    through the control API (UpdateCluster with a bumped CAConfig
    ForceRotate — reference controlapi/ca_rotation.go), no internal
    ca_server calls; plus wire-level rejection of a mismatched signing
    cert/key pair."""
    m1 = cluster.add_manager()
    w1 = cluster.add_agent()
    leader = cluster.leader()

    def worker_ready():
        n = leader.store.view(lambda tx: tx.get_node(w1.node_id))
        return n is not None and n.status.state == NodeStatusState.READY

    assert wait_for(worker_ready, timeout=40)
    svc = _create_service(cluster, "pre-api-rotate", 2)
    assert wait_for(lambda: len(cluster.running(svc.id)) == 2, timeout=60)

    old_root = m1.security.root_ca.cert_pem
    ctl = cluster.control()
    try:
        # a mismatched signing cert/key is refused at the API
        from swarmkit_tpu.ca import RootCA

        a, b = RootCA.create("a"), RootCA.create("b")
        cur = ctl.list_clusters()[0]
        bad = cur.spec
        bad.ca.signing_ca_cert = a.cert_pem
        bad.ca.signing_ca_key = b.key_pem
        with pytest.raises(Exception, match="does not match"):
            ctl.update_cluster(cur.id, cur.meta.version, bad)

        # the real rotation: ForceRotate bump through UpdateCluster
        for _ in range(20):
            cur = ctl.list_clusters()[0]
            spec = cur.spec
            spec.ca.signing_ca_cert = b""
            spec.ca.signing_ca_key = b""
            spec.ca.force_rotate += 1
            try:
                ctl.update_cluster(cur.id, cur.meta.version, spec)
                break
            except Exception as exc:
                if "out of sequence" not in str(exc):
                    raise
                time.sleep(0.1)
        else:
            pytest.fail("cluster update kept conflicting")

        # rotation record exists and the epoch advanced
        c = leader.store.view(lambda tx: tx.find_clusters())[0]
        assert c.root_ca.last_forced_rotation >= 1
    finally:
        ctl.close()

    # nodes converge onto the new root with NO further API calls: the CA
    # server's reconciler drives completion exactly as for rotate_root_ca
    def renewed():
        new_root = leader.manager.ca_server.root.cert_pem
        return (new_root != old_root
                and m1.security.root_ca.cert_pem == new_root
                and w1.security.root_ca.cert_pem == new_root)

    assert wait_for(renewed, timeout=300)
    # rotation finished: record cleared, data plane still serves
    c = leader.store.view(lambda tx: tx.find_clusters())[0]
    assert not c.root_ca.root_rotation
    ctl2 = cluster.control()
    try:
        cur = ctl2.get_service(svc.id)
        cur.spec.replicas = 3
        ctl2.update_service(svc.id, cur.meta.version, cur.spec)
    finally:
        ctl2.close()
    assert wait_for(lambda: len(cluster.running(svc.id)) == 3, timeout=60)


def test_force_new_cluster_recovers_quorum_loss(cluster):
    """Disaster recovery (integration_test.go:552 TestForceNewCluster,
    raft.go ForceNewCluster): a 3-manager cluster loses quorum (2 of 3
    die), the survivor restarts with force_new_cluster=True and serves
    again as a single-member raft KEEPING the replicated state; a fresh
    manager then re-joins and replicates; the worker's tasks keep
    running throughout."""
    m1 = cluster.add_manager()
    m2 = cluster.add_manager()
    m3 = cluster.add_manager()
    w = cluster.add_agent()
    managers = [m1, m2, m3]
    assert wait_for(
        lambda: all(len(m.raft.members) == 3 for m in managers), timeout=30)

    svc = _create_service(cluster, "durable", 2)
    assert wait_for(lambda: len(cluster.running(svc.id)) == 2, timeout=45)

    leader = cluster.leader()
    followers = [m for m in managers if m is not leader]
    for f in followers:
        cluster.nodes.remove(f)
        f.stop()

    # quorum lost: the survivor cannot commit a write any more
    ctl = RemoteControl(leader.addr, leader.security)
    try:
        with pytest.raises(Exception):
            ctl.create_service(ServiceSpec(
                annotations=Annotations(name="no-quorum"), replicas=1))
    finally:
        ctl.close()

    state_dir, port = leader.state_dir, leader.advertise_addr.rsplit(":", 1)[1]
    cluster.nodes.remove(leader)
    leader.stop()
    time.sleep(0.5)

    def start_survivor():
        node = SwarmNode(
            state_dir=state_dir,
            executor=FakeExecutor({"*": {"run_forever": True}},
                                  hostname="m-survivor"),
            listen_addr="127.0.0.1:" + port,
            heartbeat_period=0.5,
            tick_interval=0.05,
            manager_refresh_interval=0.5,
            force_new_cluster=True,
        )
        node.start()
        return node

    end = time.monotonic() + 20       # OS may briefly hold the listener
    while True:
        try:
            survivor = start_survivor()
            break
        except OSError:
            if time.monotonic() >= end:
                raise
            time.sleep(0.5)
    cluster.nodes.append(survivor)

    # single-member raft serves with the replicated state intact
    assert wait_for(lambda: survivor.is_leader, timeout=60)
    assert len(survivor.raft.members) == 1
    got = survivor.store.view(lambda tx: tx.get_service(svc.id))
    assert got is not None and got.spec.annotations.name == "durable"

    # the worker re-registers against the recovered manager and its tasks
    # stay up (FakeExecutor run_forever); writes commit again
    assert wait_for(lambda: len(cluster.running(svc.id)) == 2, timeout=90)
    svc2 = _create_service(cluster, "post-recovery", 1)
    assert wait_for(lambda: len(cluster.running(svc2.id)) == 1, timeout=45)

    # a fresh manager re-joins the recovered cluster and replicates
    m_new = cluster.add_manager("m-rejoin")
    assert wait_for(lambda: len(survivor.raft.members) == 2, timeout=60)
    assert wait_for(
        lambda: m_new.store.view(lambda tx: tx.get_service(svc.id))
        is not None, timeout=60)


def test_demote_to_single_manager(cluster):
    """integration_test.go:408 TestDemoteToSingleManager — demote the
    LEADER twice in a row: 3 managers -> 2 -> 1. The second demotion is
    the edge the 3->2 test can't reach: the one remaining member must
    shrink the quorum to itself and win a single-member election."""
    m1 = cluster.add_manager()
    m2 = cluster.add_manager()
    m3 = cluster.add_manager()
    managers = [m1, m2, m3]
    assert wait_for(
        lambda: all(len(m.raft.members) == 3 for m in managers), timeout=30)

    svc = _create_service(cluster, "survives-demotions", 2)
    assert wait_for(lambda: len(cluster.running(svc.id)) == 2, timeout=45)

    first = cluster.leader()
    cluster.set_node_role(first.node_id, NodeRole.WORKER)
    rest = [m for m in managers if m is not first]
    assert wait_for(lambda: any(m.is_leader for m in rest), timeout=120)
    assert wait_for(
        lambda: all(len(m.raft.members) == 2 for m in rest), timeout=120)
    assert wait_for(lambda: first.manager is None, timeout=120)

    second = cluster.leader()
    cluster.set_node_role(second.node_id, NodeRole.WORKER)
    last = next(m for m in rest if m is not second)
    assert wait_for(lambda: last.is_leader, timeout=120)
    assert wait_for(lambda: len(last.raft.members) == 1, timeout=120)
    assert wait_for(lambda: second.manager is None, timeout=120)

    # the single-manager cluster still serves writes; both demoted nodes
    # keep working as workers (replicas can land anywhere)
    svc2 = _create_service(cluster, "single-manager", 3)
    assert wait_for(lambda: len(cluster.running(svc2.id)) == 3, timeout=60)


def test_demote_downed_manager(cluster):
    """integration_test.go:452 TestDemoteDownedManager — demote a manager
    WHILE IT IS DOWN (it cannot ack anything), then restart it from its
    state dir: the membership conf-change must commit against the
    remaining quorum, and the restarted node must discover it is no
    longer a manager and come back as a worker."""
    m1 = cluster.add_manager()
    m2 = cluster.add_manager()
    m3 = cluster.add_manager()
    managers = [m1, m2, m3]
    assert wait_for(
        lambda: all(len(m.raft.members) == 3 for m in managers), timeout=30)

    demotee = next(m for m in managers if not m.is_leader)
    node_id, state_dir = demotee.node_id, demotee.state_dir
    port = demotee.advertise_addr.rsplit(":", 1)[1]
    cluster.nodes.remove(demotee)
    demotee.stop()

    # demote the downed node: the 2-member quorum commits the role flip
    # and the conf change without the demotee's participation
    cluster.set_node_role(node_id, NodeRole.WORKER)
    live = [m for m in managers if m is not demotee]
    assert wait_for(
        lambda: all(len(m.raft.members) == 2 for m in live), timeout=120)

    # restart from the same state dir: it must realize it was demoted
    def start_back():
        node = SwarmNode(
            state_dir=state_dir,
            executor=FakeExecutor({"*": {"run_forever": True}},
                                  hostname="demoted"),
            listen_addr="127.0.0.1:" + port,
            heartbeat_period=0.5,
            tick_interval=0.05,
            manager_refresh_interval=0.5,
        )
        node.start()
        return node

    end = time.monotonic() + 20       # OS may briefly hold the listener
    while True:
        try:
            back = start_back()
            break
        except OSError:
            if time.monotonic() >= end:
                raise
            time.sleep(0.5)
    cluster.nodes.append(back)
    assert back.node_id == node_id
    assert wait_for(lambda: back.manager is None, timeout=120)

    # it serves as a worker: it re-registers READY and the quorum stays 2
    leader = cluster.leader()

    def ready_as_worker():
        n = leader.store.view(lambda tx: tx.get_node(node_id))
        return (n is not None and n.status.state == NodeStatusState.READY
                and n.role == NodeRole.WORKER)

    assert wait_for(ready_as_worker, timeout=120)
    assert all(len(m.raft.members) == 2 for m in live)


def test_restart_leader_rejoins(cluster):
    """integration_test.go:515 TestRestartLeader — stop the raft LEADER,
    let the others elect, then restart it from its state dir: it must
    come back as a MEMBER (same raft id), catch up the log, and the
    cluster serve writes with all three members again."""
    m1 = cluster.add_manager()
    m2 = cluster.add_manager()
    m3 = cluster.add_manager()
    managers = [m1, m2, m3]
    assert wait_for(
        lambda: all(len(m.raft.members) == 3 for m in managers), timeout=30)

    svc = _create_service(cluster, "pre-restart", 2)
    assert wait_for(lambda: len(cluster.running(svc.id)) == 2, timeout=45)

    leader = cluster.leader()
    old_raft_id = leader.raft_id
    state_dir = leader.state_dir
    port = leader.advertise_addr.rsplit(":", 1)[1]
    rest = [m for m in managers if m is not leader]
    cluster.nodes.remove(leader)
    leader.stop()

    assert wait_for(lambda: any(m.is_leader for m in rest), timeout=120)

    # a write commits while the old leader is down (quorum 2 of 3)
    svc2 = _create_service(cluster, "while-down", 1)
    assert wait_for(lambda: len(cluster.running(svc2.id)) == 1, timeout=60)

    def start_back():
        node = SwarmNode(
            state_dir=state_dir,
            executor=FakeExecutor({"*": {"run_forever": True}},
                                  hostname="old-leader"),
            listen_addr="127.0.0.1:" + port,
            heartbeat_period=0.5,
            tick_interval=0.05,
            manager_refresh_interval=0.5,
        )
        node.start()
        return node

    end = time.monotonic() + 20
    while True:
        try:
            back = start_back()
            break
        except OSError:
            if time.monotonic() >= end:
                raise
            time.sleep(0.5)
    cluster.nodes.append(back)
    assert back.raft_id == old_raft_id

    # rejoined as a member and caught up the log written while it was down
    assert wait_for(
        lambda: back.manager is not None
        and len(back.raft.members) == 3, timeout=120)
    assert wait_for(
        lambda: back.store.view(lambda tx: tx.get_service(svc2.id))
        is not None, timeout=60)
    svc3 = _create_service(cluster, "post-restart", 1)
    assert wait_for(lambda: len(cluster.running(svc3.id)) == 1, timeout=60)


def test_repeated_root_rotation(cluster):
    """integration_test.go:735 TestRepeatedRootRotation — a SECOND root
    rotation after the first fully converged: every node must land on
    the final root (rotation epochs advance, no node stuck trusting a
    superseded root) and the data plane keep serving."""
    m1 = cluster.add_manager()
    w1 = cluster.add_agent()
    leader = cluster.leader()

    def worker_ready():
        n = leader.store.view(lambda tx: tx.get_node(w1.node_id))
        return n is not None and n.status.state == NodeStatusState.READY

    assert wait_for(worker_ready, timeout=40)
    svc = _create_service(cluster, "pre-rotations", 2)
    assert wait_for(lambda: len(cluster.running(svc.id)) == 2, timeout=60)

    def rotate_and_converge():
        old_root = leader.manager.ca_server.root.cert_pem
        leader.manager.ca_server.rotate_root_ca()

        def renewed():
            new_root = leader.manager.ca_server.root.cert_pem
            return (new_root != old_root
                    and m1.security.root_ca.cert_pem == new_root
                    and w1.security.root_ca.cert_pem == new_root)

        # same generous window as the single-rotation test: each renewal
        # chain hop has its own timer and CI load stretches all of them
        assert wait_for(renewed, timeout=300)

    rotate_and_converge()
    rotate_and_converge()

    # two full rotations later the data plane still serves (window sized
    # like the single-rotation sibling: renewal chains stretch under load)
    ctl = cluster.control()
    try:
        cur = ctl.get_service(svc.id)
        cur.spec.replicas = 4
        ctl.update_service(svc.id, cur.meta.version, cur.spec)
    finally:
        ctl.close()
    assert wait_for(lambda: len(cluster.running(svc.id)) == 4, timeout=120)
