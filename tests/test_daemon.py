"""SwarmNode daemon assembly over real TCP + mTLS (in one process).

The scenarios the VERDICT's item-1 'done' criterion names, at in-process
scope (the subprocess tier lives in test_multiprocess.py): managers form a
raft quorum over the network transport, workers join with a token and a
digest-pinned root fetch, services reach RUNNING through the wire
dispatcher, and the cluster survives losing its leader.
"""
import os
import time

import pytest

from swarmkit_tpu.agent.testutils import FakeExecutor
from swarmkit_tpu.api.specs import Annotations, ServiceSpec
from swarmkit_tpu.api.types import TaskState
from swarmkit_tpu.node.daemon import SwarmNode
from swarmkit_tpu.rpc.services import RemoteControl
from swarmkit_tpu.store import by as by_mod

from test_scheduler import wait_for  # noqa: E402 (tests/ path via conftest)


pytestmark = pytest.mark.daemon


def _mk_manager(tmp_path, name, join_addr=None, join_token=None):
    node = SwarmNode(
        state_dir=str(tmp_path / name),
        executor=FakeExecutor({"*": {"run_forever": True}}, hostname=name),
        listen_addr="127.0.0.1:0",
        join_addr=join_addr,
        join_token=join_token,
        heartbeat_period=0.5,
        tick_interval=0.05,
        manager_refresh_interval=0.5,
    )
    node.start()
    return node


def _mk_worker(tmp_path, name, join_addr, join_token):
    node = SwarmNode(
        state_dir=str(tmp_path / name),
        executor=FakeExecutor({"*": {"run_forever": True}}, hostname=name),
        join_addr=join_addr,
        join_token=join_token,
        heartbeat_period=0.5,
        manager_refresh_interval=0.5,
    )
    node.start()
    return node


def _tokens(manager: SwarmNode):
    # leadership application (and cluster seeding) is asynchronous with the
    # raft role flip — wait for the seeded cluster object
    def seeded():
        c = manager.store.view(
            lambda tx: tx.get_cluster(manager.manager.cluster_id))
        return c is not None and c.root_ca is not None
    assert wait_for(seeded, timeout=10)
    cluster = manager.store.view(
        lambda tx: tx.get_cluster(manager.manager.cluster_id))
    return (cluster.root_ca.join_token_manager,
            cluster.root_ca.join_token_worker)


def _running_count(store, service_id):
    from swarmkit_tpu.store import by

    tasks = store.view(lambda tx: tx.find_tasks(by.ByServiceID(service_id)))
    return sum(1 for t in tasks if t.status.state == TaskState.RUNNING)


@pytest.fixture
def cluster_nodes():
    nodes = []
    yield nodes
    for n in reversed(nodes):
        try:
            n.stop()
        except Exception:
            pass


def test_single_manager_service_over_wire(tmp_path, cluster_nodes):
    m1 = _mk_manager(tmp_path, "m1")
    cluster_nodes.append(m1)
    assert wait_for(lambda: m1.is_leader, timeout=10)

    ctl = RemoteControl(m1.addr, m1.security)
    try:
        spec = ServiceSpec(annotations=Annotations(name="web"), replicas=3)
        svc = ctl.create_service(spec)
        assert wait_for(lambda: _running_count(m1.store, svc.id) == 3,
                        timeout=45)
        # the manager's own agent ran them (managers run workloads too)
        listed = ctl.list_services()
        assert [s.id for s in listed] == [svc.id]
    finally:
        ctl.close()


def test_worker_join_and_schedule(tmp_path, cluster_nodes):
    m1 = _mk_manager(tmp_path, "m1")
    cluster_nodes.append(m1)
    assert wait_for(lambda: m1.is_leader, timeout=10)
    _mtok, wtok = _tokens(m1)

    w1 = _mk_worker(tmp_path, "w1", m1.addr, wtok)
    cluster_nodes.append(w1)

    # worker registered over the wire and became READY
    def worker_ready():
        n = m1.store.view(lambda tx: tx.get_node(w1.node_id))
        from swarmkit_tpu.api.types import NodeStatusState

        return n is not None and n.status.state == NodeStatusState.READY

    assert wait_for(worker_ready, timeout=15)

    ctl = RemoteControl(m1.addr, m1.security)
    try:
        spec = ServiceSpec(annotations=Annotations(name="spread"), replicas=6)
        svc = ctl.create_service(spec)
        assert wait_for(lambda: _running_count(m1.store, svc.id) == 6,
                        timeout=45)
        # both nodes actually run tasks (spread over 2 nodes)
        from swarmkit_tpu.store import by

        tasks = m1.store.view(
            lambda tx: tx.find_tasks(by.ByServiceID(svc.id)))
        nodes_used = {t.node_id for t in tasks
                      if t.status.state == TaskState.RUNNING}
        assert len(nodes_used) == 2
    finally:
        ctl.close()


def test_three_manager_quorum_and_leader_failover(tmp_path, cluster_nodes):
    m1 = _mk_manager(tmp_path, "m1")
    cluster_nodes.append(m1)
    assert wait_for(lambda: m1.is_leader, timeout=10)
    mtok, wtok = _tokens(m1)

    m2 = _mk_manager(tmp_path, "m2", join_addr=m1.addr, join_token=mtok)
    cluster_nodes.append(m2)
    m3 = _mk_manager(tmp_path, "m3", join_addr=m1.addr, join_token=mtok)
    cluster_nodes.append(m3)
    managers = [m1, m2, m3]

    # all three replicate the member list
    assert wait_for(
        lambda: all(len(m.raft.members) == 3 for m in managers), timeout=30)

    w1 = _mk_worker(tmp_path, "w1",
                    ",".join(m.addr for m in managers), wtok)
    cluster_nodes.append(w1)

    # a write against a *follower* forwards to the leader transparently
    follower = next(m for m in managers if not m.is_leader)
    ctl = RemoteControl(follower.addr, follower.security)
    try:
        spec = ServiceSpec(annotations=Annotations(name="ha"), replicas=8)
        svc = ctl.create_service(spec)
    finally:
        ctl.close()

    leader = next(m for m in managers if m.is_leader)
    assert wait_for(lambda: _running_count(leader.store, svc.id) == 8,
                    timeout=60)

    # ---- kill the leader process ----------------------------------------
    cluster_nodes.remove(leader)
    leader.stop()
    survivors = [m for m in managers if m is not leader]

    assert wait_for(lambda: any(m.is_leader for m in survivors), timeout=60)
    new_leader = next(m for m in survivors if m.is_leader)

    # control plane is responsive again and replicas converge back to 8
    # (tasks that ran on the dead leader's agent get rescheduled once its
    # heartbeats lapse)
    def converged():
        nl = next((m for m in survivors if m.is_leader), new_leader)
        return _running_count(nl.store, svc.id) == 8

    # full-suite runs on a loaded machine starve these threads for long
    # stretches; the window is generous because wait_for returns early
    if not wait_for(converged, timeout=120):
        import collections

        nl = next((m for m in survivors if m.is_leader), new_leader)
        tasks = nl.store.view(
            lambda tx: tx.find_tasks(by_mod.ByServiceID(svc.id)))
        states = collections.Counter(
            (int(t.status.state), int(t.desired_state), t.node_id[:6] or "-")
            for t in tasks)
        nodes_dump = {n.id[:6]: int(n.status.state)
                      for n in nl.store.view(lambda tx: tx.find_nodes())}
        raft_dump = {m.node_id[:6]: m.raft.status() for m in survivors}
        raise AssertionError(
            f"no convergence: tasks(state,desired,node)={dict(states)} "
            f"nodes={nodes_dump} raft={raft_dump} "
            f"sessions={list(nl.manager.dispatcher._sessions)}")

    # the worker's session works against the new leader: it is READY again
    # and runs tasks of a service created *after* the failover. (Its old
    # tasks may legitimately live elsewhere now — if its re-registration
    # lost the grace race they were rescheduled, and nothing rebalances.)
    nl = next((m for m in survivors if m.is_leader), new_leader)

    def worker_ready_again():
        from swarmkit_tpu.api.types import NodeStatusState

        n = nl.store.view(lambda tx: tx.get_node(w1.node_id))
        return n is not None and n.status.state == NodeStatusState.READY

    assert wait_for(worker_ready_again, timeout=45)

    ctl2 = RemoteControl(nl.addr, nl.security)
    try:
        post = ctl2.create_service(
            ServiceSpec(annotations=Annotations(name="post-failover"),
                        replicas=6))
    finally:
        ctl2.close()

    def worker_runs_new_service():
        tasks = nl.store.view(
            lambda tx: tx.find_tasks(by_mod.ByServiceID(post.id)))
        return any(t.node_id == w1.node_id
                   and t.status.state == TaskState.RUNNING for t in tasks)

    assert wait_for(worker_runs_new_service, timeout=30)


def test_restarted_manager_rejoins_from_state_dir(tmp_path, cluster_nodes):
    m1 = _mk_manager(tmp_path, "m1")
    cluster_nodes.append(m1)
    assert wait_for(lambda: m1.is_leader, timeout=10)
    mtok, _ = _tokens(m1)

    m2 = _mk_manager(tmp_path, "m2", join_addr=m1.addr, join_token=mtok)
    cluster_nodes.append(m2)
    assert wait_for(lambda: len(m1.raft.members) == 2, timeout=15)

    ctl = RemoteControl(m1.addr, m1.security)
    try:
        svc = ctl.create_service(
            ServiceSpec(annotations=Annotations(name="durable"), replicas=2))
    finally:
        ctl.close()
    assert wait_for(lambda: _running_count(m1.store, svc.id) == 2, timeout=45)

    # restart m2 from its state dir: same identity, same raft id, catches up
    old_id, old_raft_id = m2.node_id, m2.raft_id
    cluster_nodes.remove(m2)
    m2.stop()
    time.sleep(0.5)
    state_dir = m2.state_dir
    def start_m2b():
        node = SwarmNode(
            state_dir=state_dir,
            executor=FakeExecutor({"*": {"run_forever": True}},
                                  hostname="m2"),
            listen_addr="127.0.0.1:" + m2.advertise_addr.rsplit(":", 1)[1],
            heartbeat_period=0.5,
            tick_interval=0.05,
        )
        node.start()
        return node

    # the OS can hold the old listener briefly after stop; retry like a
    # process supervisor would
    end = time.monotonic() + 15
    while True:
        try:
            m2b = start_m2b()
            break
        except OSError:
            if time.monotonic() >= end:
                raise
            time.sleep(0.5)
    cluster_nodes.append(m2b)
    assert m2b.node_id == old_id
    assert m2b.raft_id == old_raft_id

    def caught_up():
        got = m2b.store.view(lambda tx: tx.get_service(svc.id))
        return got is not None

    assert wait_for(caught_up, timeout=45)


def test_worker_promotion_and_demotion_over_wire(tmp_path, cluster_nodes):
    """node promote → the worker renews to a manager cert, joins the raft
    quorum, and serves the control plane; node demote reverses it
    (node/node.go superviseManager + role_manager.go over the session
    message plane)."""
    from swarmkit_tpu.api.types import NodeRole

    m1 = _mk_manager(tmp_path, "m1")
    cluster_nodes.append(m1)
    assert wait_for(lambda: m1.is_leader, timeout=10)
    _mtok, wtok = _tokens(m1)

    w1 = _mk_worker(tmp_path, "w1", m1.addr, wtok)
    cluster_nodes.append(w1)

    def worker_ready():
        n = m1.store.view(lambda tx: tx.get_node(w1.node_id))
        from swarmkit_tpu.api.types import NodeStatusState

        return n is not None and n.status.state == NodeStatusState.READY

    assert wait_for(worker_ready, timeout=15)

    def set_role(node_id, role):
        """Version-checked update raced by status writers: retry on
        sequence conflicts like any real client."""
        ctl = RemoteControl(m1.addr, m1.security)
        try:
            for _ in range(20):
                n = ctl.get_node(node_id)
                n.spec.desired_role = role
                try:
                    ctl.update_node(n.id, n.meta.version, n.spec)
                    return
                except Exception as exc:
                    if "out of sequence" not in str(exc):
                        raise
                    time.sleep(0.1)
            raise AssertionError("could not update node role")
        finally:
            ctl.close()

    # promote via the control plane
    set_role(w1.node_id, NodeRole.MANAGER)

    assert wait_for(lambda: w1.manager is not None and w1.raft is not None,
                    timeout=40), "worker never became a manager"
    assert wait_for(lambda: len(m1.raft.members) == 2, timeout=45)
    assert wait_for(
        lambda: w1.security.role() == NodeRole.MANAGER, timeout=10)

    # the promoted manager replicates state
    def replicated():
        return (w1.store is not None
                and w1.store.view(lambda tx: tx.find_clusters()))

    assert wait_for(replicated, timeout=45)

    # demote: quorum shrinks back, stack tears down, cert returns to worker
    set_role(w1.node_id, NodeRole.WORKER)

    assert wait_for(lambda: len(m1.raft.members) == 1, timeout=40)
    assert wait_for(lambda: w1.manager is None and w1.raft is None,
                    timeout=40)
    assert wait_for(
        lambda: w1.security.role() == NodeRole.WORKER, timeout=45)

    # re-promotion joins cleanly (the raft state dir was wiped on
    # demotion; a stale WAL would poison the fresh raft id)
    set_role(w1.node_id, NodeRole.MANAGER)
    assert wait_for(lambda: w1.manager is not None and w1.raft is not None,
                    timeout=40)
    assert wait_for(lambda: len(m1.raft.members) == 2, timeout=45)
    assert wait_for(lambda: replicated(), timeout=45)


def test_join_rejection_policy_mixed_seeds(tmp_path, monkeypatch):
    """A server-side token rejection fails fast ONLY when no seed gave a
    non-rejection response that pass: unreachable seeds don't vote (a
    rejection + a dead seed is still final), but any seed answering
    differently keeps the retry loop alive — one deposed manager's stale
    verdict must not permanently fail a join the real leader would accept.
    And the final error always surfaces the rejection verdict, not a later
    transient."""
    from swarmkit_tpu.ca.certificates import RootCA
    from swarmkit_tpu.ca.config import generate_join_token
    from swarmkit_tpu.node import daemon as daemon_mod
    from swarmkit_tpu.node.daemon import NodeError, SwarmNode
    from swarmkit_tpu.rpc.wire import RPCError

    root = RootCA.create("join-policy-org")
    token = generate_join_token(root)
    calls = []

    class FakeRemoteCA:
        def __init__(self, addr, root_cert_pem=None):
            self.addr = addr

        def issue_node_certificate(self, csr_pem, token=None, node_id=None):
            calls.append(self.addr)
            if self.addr.startswith("reject"):
                raise RPCError("InvalidToken", "token rejected")
            raise ConnectionRefusedError("seed down")

        def close(self):
            pass

    monkeypatch.setattr(daemon_mod, "RemoteCA", FakeRemoteCA)
    monkeypatch.setattr(daemon_mod, "fetch_root_cert",
                        lambda addr, digest, **kw: root.cert_pem)
    monkeypatch.setattr(daemon_mod, "JOIN_TIMEOUT", 1.0)
    monkeypatch.setattr(daemon_mod, "JOIN_RETRY", 0.05)

    def make_node(seeds):
        n = SwarmNode(state_dir=str(tmp_path / "n"), executor=None,
                      join_addr=seeds, join_token=token,
                      org="join-policy-org")
        return n

    # rejection + unreachable seed: the rejection is the only RESPONSE,
    # so it is final on the first pass (fail-fast holds) and names the
    # verdict
    calls.clear()
    n = make_node("reject-a:1,dead-b:2")
    t0 = time.monotonic()
    with pytest.raises(NodeError, match="join rejected"):
        n._obtain_identity()
    assert time.monotonic() - t0 < 0.5          # no retry-window burn
    assert calls == ["reject-a:1", "dead-b:2"]  # single pass, both tried

    # all seeds reject: final on the first pass
    calls.clear()
    n = make_node("reject-a:1,reject-b:2")
    with pytest.raises(NodeError, match="join rejected"):
        n._obtain_identity()
    assert calls == ["reject-a:1", "reject-b:2"]

    # a rejection plus a seed answering NOT-REJECTED (server-side issuance
    # timeout) keeps retrying until the window closes — and the final
    # error still surfaces the rejection, not the other seed's state
    class PendingRemoteCA(FakeRemoteCA):
        def issue_node_certificate(self, csr_pem, token=None, node_id=None):
            calls.append(self.addr)
            if self.addr.startswith("reject"):
                raise RPCError("InvalidToken", "token rejected")
            return "node-id"

        def node_certificate_status(self, node_id, timeout=None):
            return None                          # never issued

    monkeypatch.setattr(daemon_mod, "RemoteCA", PendingRemoteCA)
    calls.clear()
    n = make_node("reject-a:1,pending-b:2")
    with pytest.raises(NodeError, match="join rejected"):
        n._obtain_identity()
    assert len(calls) >= 4                       # multiple passes ran

    # a rejection plus a seed ANSWERING with a transient wire error
    # (NotLeaderError mid-election surfaces as RPCError) must keep
    # retrying — one deposed manager's stale verdict is not final while
    # a live seed is still looking for its leader
    class ElectionRemoteCA(FakeRemoteCA):
        def issue_node_certificate(self, csr_pem, token=None, node_id=None):
            calls.append(self.addr)
            if self.addr.startswith("reject"):
                raise RPCError("InvalidToken", "token rejected")
            raise RPCError("NotLeaderError", "no reachable raft leader")

    monkeypatch.setattr(daemon_mod, "RemoteCA", ElectionRemoteCA)
    calls.clear()
    n = make_node("reject-a:1,electing-b:2")
    with pytest.raises(NodeError, match="join rejected"):
        n._obtain_identity()
    assert len(calls) >= 4                       # retried past pass 1
