"""Tier-1 promotion of the multichip dry run (ISSUE 7 satellite).

`python -c "import __graft_entry__; __graft_entry__.dryrun_multichip(8)"`
was the only thing exercising the full mesh pipeline end-to-end (sharded
placement, the production mesh Scheduler, the TickPipeline over a mesh
ResidentPlacement, the sharded raft tally, and the fused flagship) — a
mesh regression could ride a green pytest run, which is exactly what
happened at this round's seed (jax.sharding.set_mesh absent). This runs
the SAME function in-process on the conftest's 8 virtual devices.

The scale-out stage runs at a reduced shape here so tier-1 stays inside
its time budget; the driver's MULTICHIP command keeps the full
131072 × 1M grid (the defaults)."""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))


def test_dryrun_multichip_8(capsys):
    import __graft_entry__

    __graft_entry__.dryrun_multichip(8, scaleout_nodes=8 * 2048,
                                     scaleout_tasks=131_072)
    out = capsys.readouterr().out
    assert "placement parity ok" in out
    assert "SCALE-OUT fused step ok" in out
    assert "strategies ok (binpack=" in out
