"""Operator CLI tier: swarmctl / swarm-rafttool / swarm-bench against a real
swarmd daemon process (reference swarmd/cmd/swarmctl + swarm-rafttool +
cmd/swarm-bench)."""
import json
import os
import re
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.multiprocess


def _env():
    env = dict(os.environ)
    # strip the axon sitecustomize: it imports jax at interpreter start
    # (~1.9 s) in EVERY subprocess, and the CLI tier spawns dozens —
    # these daemons schedule tiny clusters on the CPU path and the
    # framework defers jax imports until a tick actually crosses the
    # accelerator threshold
    pp = [p for p in env.get("PYTHONPATH", "").split(":")
          if p and "axon_site" not in p]
    env["PYTHONPATH"] = ":".join([REPO] + pp)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    return env


def _ctl(addr, identity, *args, check=True, timeout=60):
    r = subprocess.run(
        [sys.executable, "-m", "swarmkit_tpu.cmd.swarmctl",
         "--addr", addr, "--identity", identity, *args],
        capture_output=True, text=True, env=_env(), cwd=REPO,
        timeout=timeout)
    if check:
        assert r.returncode == 0, f"swarmctl {args}: {r.stderr}"
    return r.stdout


@pytest.fixture(scope="module")
def daemon(tmp_path_factory):
    base = tmp_path_factory.mktemp("cli")
    state = str(base / "m1")
    logf = open(base / "m1.out", "wb")
    proc = subprocess.Popen(
        [sys.executable, "-m", "swarmkit_tpu.cmd.swarmd",
         "--state-dir", state, "--listen-addr", "127.0.0.1:0",
         "--heartbeat-period", "0.5", "--tick-interval", "0.05"],
        stdout=logf, stderr=subprocess.STDOUT, env=_env(), cwd=REPO)
    addr = None
    end = time.monotonic() + 90
    while time.monotonic() < end:
        log = open(base / "m1.out").read()
        m = re.search(r"SWARM_NODE_READY addr=(\S+)", log)
        if m:
            addr = m.group(1)
            break
        assert proc.poll() is None, log
        time.sleep(0.2)
    assert addr, "daemon never became ready"
    yield {"addr": addr, "identity": state, "proc": proc,
           "base": str(base)}
    proc.terminate()
    try:
        proc.wait(timeout=5)
    except subprocess.TimeoutExpired:
        proc.kill()


def test_service_lifecycle_via_cli(daemon):
    addr, ident = daemon["addr"], daemon["identity"]
    svc_id = _ctl(addr, ident, "service", "create", "--name", "web",
                  "--command", "sleep 600", "--replicas", "2").strip()
    assert svc_id

    end = time.monotonic() + 30
    while time.monotonic() < end:
        out = _ctl(addr, ident, "service", "ls")
        if "2/2" in out:
            break
        time.sleep(0.5)
    assert "2/2" in _ctl(addr, ident, "service", "ls")

    out = _ctl(addr, ident, "task", "ls", "--service", "web")
    assert out.count("running") >= 2

    _ctl(addr, ident, "service", "scale", "web=4")
    end = time.monotonic() + 30
    while time.monotonic() < end:
        if "4/4" in _ctl(addr, ident, "service", "ls"):
            break
        time.sleep(0.5)
    assert "4/4" in _ctl(addr, ident, "service", "ls")

    inspect = json.loads(_ctl(addr, ident, "service", "inspect", "web"))
    assert inspect["replicas"] == 4
    assert inspect["command"] == ["sleep", "600"]

    _ctl(addr, ident, "service", "rm", "web")
    assert "web" not in _ctl(addr, ident, "service", "ls")


def test_node_and_cluster_and_secrets_via_cli(daemon):
    addr, ident = daemon["addr"], daemon["identity"]
    out = _ctl(addr, ident, "node", "ls")
    assert "ready" in out and "leader" in out

    clusters = json.loads(_ctl(addr, ident, "cluster", "inspect"))
    assert clusters[0]["worker_join_token"].startswith("SWMTKN-")

    _ctl(addr, ident, "secret", "create", "apikey", "--data", "s3cret")
    assert "apikey" in _ctl(addr, ident, "secret", "ls")
    _ctl(addr, ident, "config", "create", "appcfg", "--data", "x=1")
    assert "appcfg" in _ctl(addr, ident, "config", "ls")
    _ctl(addr, ident, "secret", "rm", "apikey")
    assert "apikey" not in _ctl(addr, ident, "secret", "ls")


def test_node_update_labels_and_service_update_env(daemon):
    """reference swarmctl/node/update.go (label flags) and the service
    update env/constraint/label surface."""
    addr, ident = daemon["addr"], daemon["identity"]
    node_id = _ctl(addr, ident, "node", "ls").splitlines()[1].split()[0]

    _ctl(addr, ident, "node", "update", node_id,
         "--label-add", "tier=gold", "--label-add", "zone=z1")
    info = json.loads(_ctl(addr, ident, "node", "inspect", node_id))
    assert info["labels"] == {"tier": "gold", "zone": "z1"}
    _ctl(addr, ident, "node", "update", node_id, "--label-rm", "zone")
    info = json.loads(_ctl(addr, ident, "node", "inspect", node_id))
    assert info["labels"] == {"tier": "gold"}
    # no-op update is refused (reference errNoChange)
    r = subprocess.run(
        [sys.executable, "-m", "swarmkit_tpu.cmd.swarmctl",
         "--addr", addr, "--identity", ident, "node", "update", node_id],
        capture_output=True, text=True, env=_env(), cwd=REPO, timeout=60)
    assert r.returncode != 0 and "no change" in (r.stdout + r.stderr)

    _ctl(addr, ident, "service", "create", "--name", "upenv",
         "--command", "sleep 600", "--replicas", "1")
    _ctl(addr, ident, "service", "update", "upenv",
         "--env", "A=1", "--env", "B=2", "--label-add", "team=core")
    # constraint replacement goes through create-time validation too
    r = subprocess.run(
        [sys.executable, "-m", "swarmkit_tpu.cmd.swarmctl",
         "--addr", addr, "--identity", ident, "service", "update",
         "upenv", "--env", "X={{.Bogus}}"],
        capture_output=True, text=True, env=_env(), cwd=REPO, timeout=60)
    assert r.returncode != 0
    _ctl(addr, ident, "service", "rm", "upenv")


def test_swarmbench_and_rafttool(daemon):
    addr, ident = daemon["addr"], daemon["identity"]
    r = subprocess.run(
        [sys.executable, "-m", "swarmkit_tpu.cmd.swarmbench",
         "--addr", addr, "--identity", ident, "--replicas", "10",
         "--timeout", "60"],
        capture_output=True, text=True, env=_env(), cwd=REPO, timeout=120)
    assert r.returncode == 0, r.stderr
    stats = json.loads(r.stdout)
    assert stats["running"] == 10
    assert stats["time_to_all_s"] is not None

    # rafttool reads the stopped daemon's encrypted WAL — run against a COPY
    # of the state dir so the live daemon keeps its lock illusion intact
    import shutil

    snap = os.path.join(daemon["base"], "statecopy")
    shutil.copytree(ident, snap,
                    ignore=shutil.ignore_patterns("*.sock"))
    r = subprocess.run(
        [sys.executable, "-m", "swarmkit_tpu.cmd.rafttool", "dump",
         "--state-dir", snap],
        capture_output=True, text=True, env=_env(), cwd=REPO, timeout=60)
    assert r.returncode == 0, r.stderr
    dump = json.loads(r.stdout)
    assert dump["commit_index"] > 0
    assert dump["members"]

    r = subprocess.run(
        [sys.executable, "-m", "swarmkit_tpu.cmd.rafttool", "dump-object",
         "--state-dir", snap, "--kind", "clusters"],
        capture_output=True, text=True, env=_env(), cwd=REPO, timeout=60)
    assert r.returncode == 0, r.stderr
    assert '"default"' in r.stdout


def test_external_ca_example_server(tmp_path):
    """The demo external CA (swarmd/cmd/external-ca-example): mints a root,
    serves cfssl-style /sign, and the ExternalCA client gets back certs
    chaining to the published root."""
    import shutil

    state = str(tmp_path / "extca")
    logf = open(tmp_path / "extca.out", "wb")
    proc = subprocess.Popen(
        [sys.executable, "-m", "swarmkit_tpu.cmd.external_ca_example",
         "--state-dir", state, "--listen", "127.0.0.1:0"],
        stdout=logf, stderr=subprocess.STDOUT, env=_env(), cwd=REPO)
    try:
        url = None
        end = time.monotonic() + 30
        while time.monotonic() < end:
            log = open(tmp_path / "extca.out").read()
            m = re.search(r"url=(\S+)", log)
            if m:
                url = m.group(1)
                break
            assert proc.poll() is None, log
            time.sleep(0.2)
        assert url

        sys.path.insert(0, REPO)
        from swarmkit_tpu.api.types import NodeRole
        from swarmkit_tpu.ca import RootCA, create_csr
        from swarmkit_tpu.ca.external import ExternalCA

        with open(os.path.join(state, "rootca.pem"), "rb") as f:
            root = RootCA(f.read())
        _, csr = create_csr("node-x", NodeRole.WORKER, "swarmkit-tpu")
        cert = ExternalCA(url).sign(csr)
        assert root.verify_cert(cert).node_id == "node-x"
        # restart reuses the SAME root from the state dir
        proc.terminate()
        proc.wait(timeout=5)
        proc2 = subprocess.Popen(
            [sys.executable, "-m", "swarmkit_tpu.cmd.external_ca_example",
             "--state-dir", state, "--listen", "127.0.0.1:0"],
            stdout=logf, stderr=subprocess.STDOUT, env=_env(), cwd=REPO)
        try:
            with open(os.path.join(state, "rootca.pem"), "rb") as f:
                assert f.read() == root.cert_pem
        finally:
            proc2.terminate()
            try:
                proc2.wait(timeout=5)
            except subprocess.TimeoutExpired:
                proc2.kill()
    finally:
        if proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                proc.kill()
        shutil.rmtree(state, ignore_errors=True)


def test_volume_commands_via_cli(daemon):
    addr, ident = daemon["addr"], daemon["identity"]
    _ctl(addr, ident, "volume", "create", "data-vol", "--driver", "dir-csi")
    out = _ctl(addr, ident, "volume", "ls")
    assert "data-vol" in out and "dir-csi" in out
    # no plugin attached to this daemon: the volume sits in <creating>
    assert "<creating>" in out
    _ctl(addr, ident, "volume", "rm", "data-vol")
    # no plugin to finish the teardown: the volume shows as removing
    # (it still reserves its name, so hiding it would be misleading)
    assert "<removing>" in _ctl(addr, ident, "volume", "ls")


def test_scheduler_backend_flags(tmp_path):
    """--scheduler-backend jax --jax-threshold 1 must flow swarmd →
    SwarmNode → Manager → Scheduler: with the product threshold at 1 the
    daemon's scheduler takes the accelerator path even for a toy service,
    and tasks still reach running (SURVEY §7 --scheduler-backend)."""
    state = str(tmp_path / "m1")
    logf = open(tmp_path / "m1.out", "wb")
    proc = subprocess.Popen(
        [sys.executable, "-m", "swarmkit_tpu.cmd.swarmd",
         "--state-dir", state, "--listen-addr", "127.0.0.1:0",
         "--heartbeat-period", "0.5", "--tick-interval", "0.05",
         "--executor", "fake",
         "--scheduler-backend", "jax", "--jax-threshold", "1"],
        stdout=logf, stderr=subprocess.STDOUT, env=_env(), cwd=REPO)
    try:
        addr = None
        end = time.monotonic() + 90
        while time.monotonic() < end:
            log = open(tmp_path / "m1.out").read()
            m = re.search(r"SWARM_NODE_READY addr=(\S+)", log)
            if m:
                addr = m.group(1)
                break
            assert proc.poll() is None, log
            time.sleep(0.2)
        assert addr, "daemon never became ready"
        _ctl(addr, state, "service", "create", "--name", "tiny",
             "--command", "sleep 600", "--replicas", "2")
        end = time.monotonic() + 60   # first jax compile happens in-daemon
        while time.monotonic() < end:
            if "2/2" in _ctl(addr, state, "service", "ls"):
                break
            time.sleep(0.5)
        assert "2/2" in _ctl(addr, state, "service", "ls")
        log = open(tmp_path / "m1.out").read()
        assert "Traceback" not in log
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=5)
        except subprocess.TimeoutExpired:
            proc.kill()


def test_node_pause_and_activate(daemon):
    """node pause blocks NEW placements but keeps existing tasks running
    (drain additionally evicts); activate restores schedulability."""
    addr, ident = daemon["addr"], daemon["identity"]
    out = _ctl(addr, ident, "node", "ls")
    node_ref = out.splitlines()[1].split()[0]

    _ctl(addr, ident, "service", "create", "--name", "pausetest",
         "--command", "sleep 600", "--replicas", "1")
    end = time.monotonic() + 30
    while time.monotonic() < end:
        if "1/1" in _ctl(addr, ident, "service", "ls"):
            break
        time.sleep(0.5)
    assert "1/1" in _ctl(addr, ident, "service", "ls")

    _ctl(addr, ident, "node", "pause", node_ref)
    # existing task keeps running on the paused node
    time.sleep(1.0)
    assert "1/1" in _ctl(addr, ident, "service", "ls")
    # new work cannot place (single-node cluster, node paused)
    _ctl(addr, ident, "service", "create", "--name", "blocked",
         "--command", "sleep 600", "--replicas", "1")
    time.sleep(2.0)

    def states(service):
        out = _ctl(addr, ident, "task", "ls", "--service", service)
        return [line.split()[2] for line in out.splitlines()[1:] if line]

    assert all(s != "running" for s in states("blocked"))
    assert "not available" in _ctl(addr, ident, "task", "ls",
                                   "--service", "blocked")

    _ctl(addr, ident, "node", "activate", node_ref)
    end = time.monotonic() + 30
    while time.monotonic() < end:
        if any(s == "running" for s in states("blocked")):
            break
        time.sleep(0.5)
    assert any(s == "running" for s in states("blocked"))
    _ctl(addr, ident, "service", "rm", "pausetest")
    _ctl(addr, ident, "service", "rm", "blocked")
