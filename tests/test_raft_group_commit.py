"""Raft group-commit plane: batched Ready flush semantics.

Pins the tentpole contracts: one WAL append + one fsync per worker batch
(not per proposal), commit callbacks firing in log order across a batch, a
mid-batch dropped proposal failing only its own callback, crash recovery of
multi-entry batched WAL appends (segmented + torn-tail repaired), fuzzed
parity between the live commit-frontier rule and the TPU replay kernel
(ops/raft_replay), the pipelined propose_async path, and the transport's
coalesced raft.step_many sends."""
from __future__ import annotations

import queue
import random
import threading
import time

import pytest

from swarmkit_tpu.raft.messages import ConfChange, Entry
from swarmkit_tpu.raft.node import LEADER, Peer, RaftNode
from swarmkit_tpu.raft.proposer import RaftProposer
from swarmkit_tpu.raft.storage import RaftStorage
from swarmkit_tpu.raft.testutils import MemoryTransport, RaftCluster


def plain_storage(tmp_path, name="r", **kw):
    return RaftStorage(str(tmp_path / name), dek=None, **kw)


# ------------------------------------------------------------ group commit


def test_batch_of_proposals_is_one_wal_fsync(tmp_path):
    s = plain_storage(tmp_path)
    c = RaftCluster(1, storages={1: s})
    leader = c.tick_until_leader()

    fsyncs0, batches0 = s.wal_fsyncs, s.append_batches
    results = []
    for k in range(100):
        leader.propose({"op": k}, f"p{k}",
                       lambda ok, err, k=k: results.append((k, ok, err)))
    leader.process_all()   # one dispatch pass + ONE Ready flush

    assert s.wal_fsyncs - fsyncs0 == 1, "group commit did not coalesce"
    assert s.append_batches - batches0 == 1
    # single-node cluster: the whole batch committed at the flush,
    # callbacks in proposal (= log) order
    assert [r[0] for r in results] == list(range(100))
    assert all(ok for _, ok, _ in results)
    assert leader.commit_index == leader._last_index()


def test_amortized_fsyncs_per_commit_below_one(tmp_path):
    """The acceptance metric: under load (many proposals per batch) total
    fsyncs — WAL and metadata — amortize to well under one per commit."""
    s = plain_storage(tmp_path)
    c = RaftCluster(1, storages={1: s})
    leader = c.tick_until_leader()

    base_fsyncs = s.wal_fsyncs + s.meta_fsyncs
    base_commits = leader.commits_applied
    for k in range(300):
        leader.propose({"op": k}, f"p{k}", lambda ok, err: None)
        if k % 75 == 74:
            leader.process_all()
    leader.process_all()
    commits = leader.commits_applied - base_commits
    fsyncs = (s.wal_fsyncs + s.meta_fsyncs) - base_fsyncs
    assert commits == 300
    assert fsyncs / commits < 1.0, (fsyncs, commits)


def test_callback_order_matches_log_order_in_cluster():
    c = RaftCluster(3)
    leader = c.tick_until_leader()
    order = []
    for k in range(50):
        leader.propose({"op": k}, f"p{k}",
                       lambda ok, err, k=k: order.append((k, ok)))
    c.settle()
    assert order == [(k, True) for k in range(50)]
    for n in c.nodes.values():
        assert n.commit_index == leader.commit_index


def test_mid_batch_dropped_proposal_fails_only_its_own_callback():
    c = RaftCluster(3)
    leader = c.tick_until_leader()
    others = sorted(i for i in c.nodes if i != leader.id)
    f, g = others
    c.router.isolate(f)   # makes remove(g) fail its quorum-safety check

    results = {}

    def cb(tag):
        return lambda ok, err: results.setdefault(tag, (ok, err))

    leader.propose({"op": "a"}, "ra", cb("a"))
    leader.propose_conf_change(
        ConfChange(action="remove", raft_id=g, node_id=f"node-{g}"),
        "rc", cb("c"))
    leader.propose({"op": "b"}, "rb", cb("b"))
    c.settle()

    assert results["a"][0] is True
    assert results["b"][0] is True
    assert results["c"][0] is False      # dropped, with a reason
    assert results["c"][1]
    # the surviving proposals committed in order despite the hole
    datas = [e.data for e in leader.log if isinstance(e.data, dict)]
    assert datas == [{"op": "a"}, {"op": "b"}]


def test_votes_persist_before_any_message_leaves(tmp_path):
    """The flush discipline: hardstate (term/vote) must hit disk before
    the buffered VoteResponse reaches the transport."""
    from swarmkit_tpu.raft.messages import VoteRequest

    s = plain_storage(tmp_path)
    router = MemoryTransport()
    n = RaftNode(raft_id=1, transport=router.for_node(1), storage=s,
                 rng=random.Random(3))
    router.register(n)
    n.bootstrap([Peer(1, "n1", "mem://1"), Peer(2, "n2", "mem://2")])

    observed = []
    orig_send = router.send

    def spy_send(frm, msg):
        st = RaftStorage(str(tmp_path / "r"), dek=None).load()
        observed.append((msg.kind, st.term if st else 0,
                         st.voted_for if st else None))
        orig_send(frm, msg)

    router.send = spy_send
    n.step(VoteRequest(frm=2, to=1, term=5, last_log_index=9,
                       last_log_term=5))
    n.process_all()
    grants = [o for o in observed if o[0] == "vote_resp"]
    assert grants, "no vote response left the node"
    kind, term_on_disk, voted_on_disk = grants[0]
    assert term_on_disk == 5 and voted_on_disk == 2


# ------------------------------------------------- crash recovery / WAL


def collect_applier(sink):
    def apply(e):
        sink.append(e.data)
    return apply


def test_crash_recovery_replays_batched_wal_append(tmp_path):
    s = plain_storage(tmp_path)
    c = RaftCluster(1, storages={1: s})
    leader = c.tick_until_leader()
    for k in range(50):
        leader.propose({"op": k}, f"p{k}", lambda ok, err: None)
    leader.process_all()   # one batched append of 50 entries
    commit = leader.commit_index
    c.nodes[1].stop()

    st = plain_storage(tmp_path).load()
    assert [e.index for e in st.entries] == list(
        range(1, leader._last_index() + 1))

    applied = []
    router = MemoryTransport()
    n = RaftNode(raft_id=1, transport=router.for_node(1),
                 storage=plain_storage(tmp_path),
                 apply_entry=collect_applier(applied),
                 rng=random.Random(1))
    router.register(n)
    assert n._last_index() >= commit
    assert applied == [{"op": k} for k in range(50)]


def test_crash_recovery_replays_batched_wal_append_encrypted(tmp_path):
    pytest.importorskip("cryptography")
    from swarmkit_tpu.raft.storage import new_dek

    dek = new_dek()
    s = RaftStorage(str(tmp_path / "enc"), dek=dek)
    c = RaftCluster(1, storages={1: s})
    leader = c.tick_until_leader()
    for k in range(20):
        leader.propose({"op": k}, f"p{k}", lambda ok, err: None)
    leader.process_all()
    c.nodes[1].stop()

    applied = []
    router = MemoryTransport()
    n = RaftNode(raft_id=1, transport=router.for_node(1),
                 storage=RaftStorage(str(tmp_path / "enc"), dek=dek),
                 apply_entry=collect_applier(applied),
                 rng=random.Random(1))
    router.register(n)
    assert applied == [{"op": k} for k in range(20)]


def test_torn_tail_is_repaired_so_later_appends_survive(tmp_path):
    """ReadRepairWAL: the tear is truncated on disk at load, so records
    appended AFTER recovery can never sit behind a corrupt record and get
    silently dropped by the next reload."""
    s = plain_storage(tmp_path)
    s.append_entries([Entry(term=1, index=i, data={"op": i})
                      for i in range(1, 6)])
    s._close_wal()

    seg = sorted((tmp_path / "r").glob("wal-*.jsonl"))[0]
    lines = seg.read_bytes().splitlines()
    assert len(lines) == 5
    lines[3] = lines[3][: len(lines[3]) // 2]    # tear record 4; 5 intact
    seg.write_bytes(b"\n".join(lines) + b"\n")

    s2 = plain_storage(tmp_path)
    st = s2.load()
    assert [e.index for e in st.entries] == [1, 2, 3]

    # post-recovery appends (a healthy leader re-replicates 4 and 5)
    s2.append_entries([Entry(term=2, index=4, data={"op": "new4"}),
                       Entry(term=2, index=5, data={"op": "new5"})])
    s2._close_wal()
    st2 = plain_storage(tmp_path).load()
    assert [(e.index, e.data) for e in st2.entries] == [
        (1, {"op": 1}), (2, {"op": 2}), (3, {"op": 3}),
        (4, {"op": "new4"}), (5, {"op": "new5"})]


def test_segmented_wal_compact_drops_whole_segments(tmp_path):
    s = plain_storage(tmp_path, segment_bytes=1)   # every batch seals
    for k in range(5):
        lo = 2 * k + 1
        s.append_entries([Entry(term=1, index=lo, data={"op": lo}),
                          Entry(term=1, index=lo + 1, data={"op": lo + 1})])
    segs = sorted((tmp_path / "r").glob("wal-*.jsonl"))
    assert len(segs) == 5

    s.compact(first_index=7)
    remaining = sorted((tmp_path / "r").glob("wal-*.jsonl"))
    assert len(remaining) == 2          # (7,8) and (9,10) survive whole
    entries = s._read_wal()
    assert [e.index for e in entries] == [7, 8, 9, 10]

    # truncate at a segment boundary: whole segment unlinked
    s.truncate_from(9)
    assert [e.index for e in s._read_wal()] == [7, 8]
    # truncate mid-segment: boundary segment rewritten
    s.truncate_from(8)
    assert [e.index for e in s._read_wal()] == [7]


def test_hard_state_save_is_fsynced(tmp_path):
    s = plain_storage(tmp_path)
    before = s.meta_fsyncs
    s.save_hard_state(term=4, voted_for=2, commit=17)
    assert s.meta_fsyncs - before >= 2    # tmp-file fsync + dir fsync
    st = plain_storage(tmp_path).load()
    assert (st.term, st.voted_for, st.commit_index) == (4, 2, 17)


# ------------------------------------------- commit-frontier replay parity


def _live_commit_frontier(frontiers: list[int], term: int = 3) -> int:
    """Drive the REAL leader commit rule (_maybe_advance_commit) with
    manager durable frontiers: frontiers[0] is the leader's own log."""
    router = MemoryTransport()
    node = RaftNode(raft_id=1, transport=router.for_node(1),
                    rng=random.Random(0))
    router.register(node)
    m = len(frontiers)
    node.bootstrap([Peer(i, f"n{i}", f"mem://{i}")
                    for i in range(1, m + 1)])
    node.term = term
    node.role = LEADER
    node.log = [Entry(term=term, index=i)
                for i in range(1, frontiers[0] + 1)]
    node.match_index = {i + 2: f for i, f in enumerate(frontiers[1:])}
    node._maybe_advance_commit()
    return node.commit_index


def test_fuzzed_commit_frontier_parity_with_replay_kernel():
    """The live quorum-tally/commit-advance rule must stay decision-
    identical to the TPU replay kernel (ops/raft_replay.replay_commit and
    match_index_commit) over random ack matrices and quorum sizes."""
    import numpy as np

    from swarmkit_tpu.ops.raft_replay import match_index_commit, replay_commit

    rng = random.Random(20250803)
    for case in range(60):
        m = rng.choice([1, 2, 3, 4, 5, 7])
        e_max = rng.randrange(1, 32)
        # the leader's own durable frontier is its whole log — a peer's
        # match index can never exceed it (replication only ships what
        # the leader has)
        frontiers = [e_max] + [rng.randrange(0, e_max + 1)
                               for _ in range(m - 1)]
        quorum = m // 2 + 1

        acks = np.zeros((m, e_max), bool)
        for i, f in enumerate(frontiers):
            acks[i, :f] = True
        kernel_commit = int(replay_commit(acks, quorum)[0])
        mi_commit = int(match_index_commit(
            np.asarray(frontiers, np.int32), quorum))
        live_commit = _live_commit_frontier(frontiers)

        assert kernel_commit == live_commit, (case, frontiers)
        # match_index_commit is the raw quorum'th-largest rule — identical
        # on prefix-contiguous acks
        assert mi_commit == kernel_commit, (case, frontiers)


# ------------------------------------------------------- pipelined propose


def test_propose_async_pipeline_shares_one_flush(tmp_path):
    s = plain_storage(tmp_path)
    c = RaftCluster(1, storages={1: s})
    leader = c.tick_until_leader()
    proposer = RaftProposer(leader)

    batches0 = s.append_batches
    order = []
    handles = [proposer.propose_async(
        [("op", k)], lambda version_index=None, k=k: order.append(k))
        for k in range(20)]
    assert not any(h.done for h in handles)
    c.settle()
    assert all(h.done for h in handles)
    for h in handles:
        h.result(timeout=0)
    assert order == list(range(20))            # commit_cbs in log order
    assert s.append_batches - batches0 == 1    # the whole window, one fsync


def test_store_batch_pipelined_replicates_and_converges():
    from swarmkit_tpu.api.objects import Task
    from swarmkit_tpu.store.memory import MemoryStore

    c = RaftCluster(3)
    stores = {}
    for i, node in c.nodes.items():
        p = RaftProposer(node)
        st = MemoryStore(proposer=p)
        p.attach_store(st)
        stores[i] = st
    leader = c.tick_until_leader()
    store = stores[leader.id]

    def run_batch():
        def fill(b):
            for k in range(30):
                t = Task(id=f"t{k}", service_id="svc")
                b.update(lambda tx, t=t: tx.create(t))
                b._flush()            # one sub-transaction per task
        store.batch(fill, pipeline_depth=8)

    err = []

    def run():
        try:
            run_batch()
        except Exception as exc:      # pragma: no cover - surfaced below
            err.append(exc)

    t = threading.Thread(target=run)
    t.start()
    deadline = time.monotonic() + 30
    while t.is_alive() and time.monotonic() < deadline:
        c.settle()
        time.sleep(0.001)
    t.join(timeout=5)
    assert not t.is_alive(), "pipelined batch never completed"
    assert not err, err
    c.settle()

    for i, st in stores.items():
        tasks = st.view().find_tasks()
        assert len(tasks) == 30, f"store {i} has {len(tasks)}"
    versions = {tuple(sorted((x.id, x.meta.version.index)
                             for x in st.view().find_tasks()))
                for st in stores.values()}
    assert len(versions) == 1, "replica version divergence"


# ------------------------------------------------------ transport batching


class _FakeClient:
    alive = True

    def __init__(self):
        self.calls = []

    def call(self, method, payload, timeout=None, **kw):
        self.calls.append((method, payload))

    def close(self):
        pass


def test_transport_sender_coalesces_backlog_into_step_many():
    pytest.importorskip("swarmkit_tpu.rpc.client",
                        reason="rpc client tier needs `cryptography`")
    from swarmkit_tpu.raft.messages import AppendEntries
    from swarmkit_tpu.raft.transport import NetworkTransport

    tr = NetworkTransport(security=None, local_raft_id=1)
    fake = _FakeClient()
    tr._client = lambda peer_id: fake

    box = queue.Queue(maxsize=64)
    msgs = [AppendEntries(frm=1, to=5, term=2, prev_log_index=k)
            for k in range(10)]
    for m in msgs:
        box.put_nowait(m)
    box.put_nowait(None)   # stop sentinel rides behind the backlog
    tr._outboxes[5] = box
    t = threading.Thread(target=tr._sender_loop, args=(5, box))
    t.start()
    t.join(timeout=5)
    assert not t.is_alive()

    delivered = []
    for method, payload in fake.calls:
        if method == "raft.step_many":
            delivered.extend(payload)
        else:
            assert method == "raft.step"
            delivered.append(payload)
    assert delivered == msgs, "messages lost or reordered"
    assert any(m == "raft.step_many" for m, _ in fake.calls), \
        "backlog was not coalesced"


def test_step_many_service_checks_removed_sender():
    from unittest.mock import MagicMock

    from swarmkit_tpu.raft.messages import AppendEntries, MemberRemovedError
    from swarmkit_tpu.rpc.services import build_manager_registry

    class _Node:
        removed_ids = {9}

        def __init__(self):
            self.stepped = []

        def step(self, msg):
            self.stepped.append(msg)

        is_leader = False
        members = {}

        def member_by_node_id(self, node_id):
            return None

    node = _Node()
    # the other planes' handlers close over the manager lazily — a mock
    # satisfies the build; only the raft plane is exercised here
    reg = build_manager_registry(MagicMock(), raft_node=node)
    handler = reg.lookup("raft.step_many").func
    ok_msgs = [AppendEntries(frm=2, to=1, term=1) for _ in range(3)]
    handler(None, ok_msgs)
    assert node.stepped == ok_msgs

    node.stepped = []
    with pytest.raises(MemberRemovedError):
        handler(None, [AppendEntries(frm=9, to=1, term=1)])
    assert node.stepped == []


# --------------------------------------------------- changes_between window


def test_changes_between_bisects_to_window():
    router = MemoryTransport()
    node = RaftNode(raft_id=1, transport=router.for_node(1),
                    rng=random.Random(0))
    router.register(node)
    proposer = RaftProposer(node)

    from swarmkit_tpu.api.objects import Version

    node.log = [Entry(term=1, index=i,
                      data=None if i % 4 == 0 else [("op", i)])
                for i in range(1, 21)]
    node.first_index = 1
    got = proposer.changes_between(Version(5), Version(12))
    assert got == [[("op", i)] for i in range(6, 13) if i % 4 != 0]
    assert proposer.changes_between(Version(20), Version(25)) == []

    # compacted window still raises (partial answers fork watchers)
    node.log = node.log[9:]
    node.first_index = 10
    from swarmkit_tpu.raft.proposer import ProposeError

    with pytest.raises(ProposeError):
        proposer.changes_between(Version(5), Version(12))
