"""HeartbeatWheel vs the per-node Heartbeat oracle (ISSUE 4 satellite).

The wheel's contract: same SET of expirations as one Heartbeat object
per key — never early, at most ~2×granularity late — with `beat()` as a
dict write (no timer objects on the steady path). All under FakeClock so
schedules are deterministic on a loaded 1-core host.
"""
import random

import pytest

from swarmkit_tpu.dispatcher.heartbeat import Heartbeat, HeartbeatWheel
from swarmkit_tpu.utils.clock import FakeClock


class CountingClock(FakeClock):
    """FakeClock that counts timer-object creations."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.timer_calls = 0

    def timer(self, delay, fn):
        self.timer_calls += 1
        return super().timer(delay, fn)


# --------------------------------------------------------------- property
@pytest.mark.parametrize("seed", range(10))
def test_wheel_matches_per_node_oracle(seed):
    """Under a randomized schedule of advances, re-arms with jittered
    periods, and stops, the wheel fires exactly the same set of keys the
    per-node Heartbeat oracle fires."""
    rng = random.Random(seed)
    clock = FakeClock()
    g = rng.choice([0.1, 0.25, 0.5])
    wheel = HeartbeatWheel(granularity=g, clock=clock)
    wheel_fired, oracle_fired, stopped = set(), set(), set()
    keys = [f"k{i}" for i in range(rng.randint(5, 20))]
    oracles = {}
    timeouts = {}
    for k in keys:
        timeouts[k] = rng.uniform(0.4, 3.0)  # jittered per-key periods
        wheel.add(k, timeouts[k], lambda k=k: wheel_fired.add(k))
        hb = Heartbeat(timeouts[k], lambda k=k: oracle_fired.add(k),
                       clock=clock)
        hb.start()
        oracles[k] = hb
    for _ in range(rng.randint(25, 70)):
        op = rng.random()
        if op < 0.5:
            clock.advance(rng.uniform(0.05, 1.3))
            # the wheel may lag the oracle by up to ~2 ticks, never lead
            assert wheel_fired <= oracle_fired
        else:
            k = rng.choice(keys)
            # only keys still live in BOTH implementations are beaten or
            # stopped (a real dispatcher can't beat an expired session
            # either — the session is gone)
            if k in wheel_fired or k in oracle_fired or k in stopped:
                continue
            if op < 0.85:
                nt = rng.uniform(0.4, 3.0)
                assert wheel.beat(k, nt)
                oracles[k].beat(nt)
            else:
                wheel.remove(k)
                oracles[k].stop()
                stopped.add(k)
    # settle: everything still armed comes due in both implementations
    clock.advance(max(timeouts.values()) + 3 * g + 5.0)
    assert wheel_fired == oracle_fired, (
        f"seed {seed}: wheel {sorted(wheel_fired)} vs oracle "
        f"{sorted(oracle_fired)}")
    assert wheel_fired.isdisjoint(stopped)
    assert len(wheel) == 0


# ------------------------------------------------------------ unit pins
def test_wheel_never_early_and_bounded_late():
    clock = FakeClock(start=1000.0)
    g = 0.5
    wheel = HeartbeatWheel(granularity=g, clock=clock)
    fired_at = []
    wheel.add("n1", 1.0, lambda: fired_at.append(clock.monotonic()))
    deadline = 1001.0
    while not fired_at and clock.monotonic() < 1010:
        clock.advance(0.05)
    assert fired_at, "entry never expired"
    assert deadline <= fired_at[0] <= deadline + 2 * g + 1e-9

def test_beat_allocates_no_timer_objects():
    clock = CountingClock()
    wheel = HeartbeatWheel(granularity=0.25, clock=clock)
    for i in range(50):
        wheel.add(f"n{i}", 10.0, lambda: None)
    assert clock.timer_calls == 1          # ONE ticker for all entries
    before = clock.timer_calls
    for _ in range(20):
        for i in range(50):
            wheel.beat(f"n{i}")
    assert clock.timer_calls == before, \
        "beat() must be a dict write, not a timer re-arm"


def test_ticker_stops_when_empty_and_rearms():
    clock = CountingClock()
    wheel = HeartbeatWheel(granularity=0.25, clock=clock)
    wheel.add("a", 1.0, lambda: None)
    wheel.remove("a")
    # ticker cancelled with the last entry: advancing fires nothing new
    clock.advance(10.0)
    ticks_idle = wheel.ticks
    clock.advance(10.0)
    assert wheel.ticks == ticks_idle
    fired = []
    wheel.add("b", 0.5, lambda: fired.append("b"))
    clock.advance(2.0)
    assert fired == ["b"]


def test_set_granularity_rebuckets_live_entries():
    clock = FakeClock(start=0.0)
    wheel = HeartbeatWheel(granularity=0.5, clock=clock)
    fired = []
    wheel.add("a", 3.0, lambda: fired.append("a"))
    wheel.set_granularity(0.05)
    clock.advance(2.0)
    assert fired == []                    # not early after re-bucketing
    clock.advance(1.2)
    assert fired == ["a"]


def test_replacing_add_swaps_callback():
    clock = FakeClock()
    wheel = HeartbeatWheel(granularity=0.25, clock=clock)
    fired = []
    wheel.add("n", 1.0, lambda: fired.append("old"))
    wheel.add("n", 1.0, lambda: fired.append("new"))
    clock.advance(5.0)
    assert fired == ["new"]


def test_stopped_wheel_is_inert():
    clock = FakeClock()
    wheel = HeartbeatWheel(granularity=0.25, clock=clock)
    fired = []
    wheel.add("n", 0.5, lambda: fired.append("n"))
    wheel.stop()
    clock.advance(5.0)
    assert fired == []
    wheel.add("m", 0.1, lambda: fired.append("m"))   # no-op, no crash
    clock.advance(5.0)
    assert fired == []


# ------------------------------------------------- sharded wheel (ISSUE 13)
def test_sharded_wheel_routes_by_stable_hash():
    """The facade routes every key to the slice `stable_shard` picks,
    aggregates len/ticks/fired across slices, and keeps the wheel
    contract per slice: beats keep an entry alive, silence expires it."""
    from swarmkit_tpu.dispatcher.heartbeat import (
        ShardedHeartbeatWheel,
        stable_shard,
    )

    clock = FakeClock()
    wheel = ShardedHeartbeatWheel(granularity=0.25, clock=clock, shards=4)
    fired = []
    keys = [f"s{i:02d}" for i in range(20)]
    for k in keys:
        wheel.add(k, 1.0, lambda k=k: fired.append(k))
    assert len(wheel) == 20
    by_slice = [len(w) for w in wheel.wheels]
    assert sum(by_slice) == 20 and sum(1 for n in by_slice if n) >= 2, \
        by_slice   # crc32 spreads 20 keys over several slices
    for k in keys:
        assert k in wheel.wheels[stable_shard(k, 4)]._timeout

    # beat half the keys forward; the silent half expires, never early
    beaten = set(keys[::2])
    clock.advance(0.75)
    for k in beaten:
        assert wheel.beat(k)
    clock.advance(0.6)     # silent keys pass 1.0s; beaten ones don't
    assert set(fired) == set(keys) - beaten
    assert wheel.fired == len(fired) and wheel.ticks > 0
    # removal routes to the owning slice
    for k in beaten:
        wheel.remove(k)
    assert len(wheel) == 0
    wheel.stop()


def test_sharded_wheel_single_slice_is_transparent():
    """shards=1 keeps the pre-sharding surface, including the debug
    attributes tests poke (`_tick`, `_ticker_gen` delegate to slice 0)."""
    from swarmkit_tpu.dispatcher.heartbeat import ShardedHeartbeatWheel

    clock = FakeClock()
    wheel = ShardedHeartbeatWheel(granularity=0.25, clock=clock, shards=1)
    fired = []
    wheel.add("n", 0.5, lambda: fired.append("n"))
    assert len(wheel) == 1 and wheel.bucket_count == 1
    wheel._tick(wheel._ticker_gen)      # delegated driving, no crash
    clock.advance(1.0)
    assert fired == ["n"]
    wheel.set_granularity(0.1)
    assert wheel.granularity == 0.1
    wheel.stop()
