"""Model-checking tier: exhaustive small-scope exploration of the task FSM
and the assignment-stream protocol, asserting the invariants the reference
verifies with TLC over its TLA+ models (design/tla/{Tasks,WorkerSpec,
WorkerImpl,EventCounter}.tla — SURVEY.md §4.5).

Instead of a separate spec language, the REAL implementation is driven
through every reachable (observed state, desired state, controller
behavior) combination:

  * monotonicity — observed state never decreases (Tasks.tla's central
    invariant; agent/exec/controller.go:163-166 panics on violation);
  * teardown priority — desired >= SHUTDOWN preempts progress;
  * fatal-error split — REJECTED strictly before STARTING, FAILED from
    STARTING on (controller.go:142-345 exec.Do);
  * terminal absorption — no transitions out of terminal states;
  * liveness under fairness — once the controller stops throwing
    TemporaryError, every trace reaches a terminal state in bounded steps.

The protocol model drives the real Dispatcher diff engine against a
shadow dict through randomized create/update/delete/reconnect
interleavings and asserts the worker-visible set always converges to the
store (WorkerSpec.tla's correspondence invariant).
"""
import itertools
import random

import pytest

from swarmkit_tpu.agent.exec import (
    ExitStatus,
    FatalError,
    TemporaryError,
    do,
)
from swarmkit_tpu.api.objects import Task
from swarmkit_tpu.api.types import TaskState

TERMINAL = {TaskState.COMPLETE, TaskState.SHUTDOWN, TaskState.FAILED,
            TaskState.REJECTED, TaskState.ORPHANED, TaskState.REMOVE}
START_STATES = [TaskState.ASSIGNED, TaskState.ACCEPTED, TaskState.PREPARING,
                TaskState.READY, TaskState.STARTING, TaskState.RUNNING]
DESIREDS = [TaskState.READY, TaskState.RUNNING, TaskState.SHUTDOWN,
            TaskState.REMOVE]
BEHAVIORS = ["ok", "temp", "fatal", "exit0", "exit1"]


class ScriptedController:
    """One FSM step's controller behavior, chosen by the explorer."""

    def __init__(self, behavior: str):
        self.behavior = behavior

    def _maybe_raise(self):
        if self.behavior == "temp":
            raise TemporaryError("transient")
        if self.behavior == "fatal":
            raise FatalError("fatal")

    def update(self, task):
        self._maybe_raise()

    def prepare(self):
        self._maybe_raise()

    def start(self):
        self._maybe_raise()

    def wait(self):
        self._maybe_raise()
        if self.behavior == "exit1":
            return ExitStatus(code=1, message="boom")
        return ExitStatus(code=0)

    def shutdown(self):
        self._maybe_raise()

    def terminate(self):
        pass

    def remove(self):
        pass

    def close(self):
        pass


def _mk_task(state, desired):
    t = Task(id="t1", service_id="s1", slot=1)
    t.status.state = state
    t.desired_state = desired
    return t


def test_exhaustive_single_steps():
    """Every (state, desired, behavior) triple: one do() step upholds the
    step invariants."""
    for state, desired, behavior in itertools.product(
            START_STATES, DESIREDS, BEHAVIORS):
        t = _mk_task(state, desired)
        status = do(t, ScriptedController(behavior))
        nxt = status.state

        # monotonicity
        assert nxt >= state, (state, desired, behavior, nxt)

        # teardown priority: desired shutdown + non-terminal observed must
        # land on SHUTDOWN unless the step errored fatally mid-teardown
        if desired >= TaskState.SHUTDOWN and state < TaskState.COMPLETE:
            if behavior in ("ok", "exit0", "exit1"):
                assert nxt == TaskState.SHUTDOWN, (state, behavior, nxt)

        # fatal split: REJECTED only before STARTING, FAILED from STARTING.
        # only steps that actually invoke the controller can observe the
        # error (ACCEPTED→PREPARING and READY→STARTING are pure moves)
        invokes_controller = state in (TaskState.ASSIGNED,
                                       TaskState.PREPARING,
                                       TaskState.STARTING,
                                       TaskState.RUNNING)
        if nxt == TaskState.REJECTED:
            assert state < TaskState.STARTING
        if behavior == "fatal" and desired < TaskState.SHUTDOWN \
                and invokes_controller:
            if state < TaskState.STARTING:
                assert nxt == TaskState.REJECTED
            elif state < TaskState.COMPLETE:
                assert nxt == TaskState.FAILED

        # temporary errors hold position, never advance past the attempt
        if behavior == "temp" and desired < TaskState.SHUTDOWN \
                and invokes_controller:
            assert nxt == state


def test_exhaustive_traces_reach_terminal():
    """BFS over every trace of up to DEPTH steps where EACH step freely
    chooses a controller behavior and the manager may flip desired state;
    invariants hold on every edge, and under fairness (behaviors 'ok'
    after the exploration horizon) every branch terminates."""
    DEPTH = 8
    seen_edges = 0
    frontier = [(state, TaskState.RUNNING)
                for state in START_STATES] + [
                (state, TaskState.READY) for state in START_STATES]
    for state0, desired0 in frontier:
        stack = [(state0, desired0, 0)]
        visited = set()
        while stack:
            state, desired, depth = stack.pop()
            if (state, desired, depth) in visited:
                continue
            visited.add((state, desired, depth))
            if state in TERMINAL:
                continue  # absorption checked below
            if depth >= DEPTH:
                # fairness closure: behaviors turn 'ok' (+ desired RUNNING
                # promotion for READY-parked tasks) — must terminate
                t_state, t_desired = state, max(desired, TaskState.RUNNING)
                for _ in range(12):
                    t = _mk_task(t_state, t_desired)
                    t_state = do(t, ScriptedController("ok")).state
                    if t_state in TERMINAL:
                        break
                assert t_state in TERMINAL, (state0, state, t_state)
                continue
            for behavior in BEHAVIORS:
                for next_desired in (desired, TaskState.SHUTDOWN):
                    t = _mk_task(state, next_desired)
                    nxt = do(t, ScriptedController(behavior)).state
                    seen_edges += 1
                    assert nxt >= state
                    stack.append((nxt, next_desired, depth + 1))
    assert seen_edges > 500  # the exploration actually covered the space


def test_terminal_states_absorb():
    for state in TERMINAL:
        for desired in DESIREDS:
            for behavior in BEHAVIORS:
                t = _mk_task(state, desired)
                status = do(t, ScriptedController(behavior))
                assert status.state == state, (state, desired, behavior)


# --------------------------------------------------------- protocol model


@pytest.mark.parametrize("seed", range(5))
def test_assignment_stream_converges(seed):
    """WorkerSpec.tla correspondence: after any interleaving of task
    create/update/delete and session reconnects, applying the dispatcher's
    COMPLETE + INCREMENTAL messages in order leaves the worker-visible task
    set equal to the store's runnable view for that node."""
    from swarmkit_tpu.api.objects import Node
    from swarmkit_tpu.api.types import NodeStatusState
    from swarmkit_tpu.store.memory import MemoryStore

    from swarmkit_tpu.dispatcher.dispatcher import Dispatcher

    rng = random.Random(seed)
    store = MemoryStore()
    n = Node(id="n1")
    n.status.state = NodeStatusState.READY
    store.update(lambda tx: tx.create(n))

    # rate limiting off: the model reconnects far faster than a real agent
    d = Dispatcher(store, heartbeat_period=30.0, rate_limit_period=0.0)
    d.start()
    shadow: dict[str, int] = {}   # task id -> version (worker view)
    try:
        sid = d.register("n1")
        session = d._sessions["n1"]

        def apply_msg(msg):
            if msg.type == "complete":
                shadow.clear()
                for a in msg.changes:
                    if a.kind == "task" and a.action == "update":
                        shadow[a.item.id] = a.item.meta.version.index
            else:
                for a in msg.changes:
                    if a.kind != "task":
                        continue
                    if a.action == "update":
                        shadow[a.item.id] = a.item.meta.version.index
                    else:
                        shadow.pop(a.item, None)

        apply_msg(d._full_assignment(session))

        live = []
        for step in range(60):
            op = rng.random()
            if op < 0.4 or not live:
                tid = f"t{step}"

                def create(tx, tid=tid):
                    t = Task(id=tid, service_id="s1", node_id="n1")
                    t.status.state = TaskState.ASSIGNED
                    t.desired_state = TaskState.RUNNING
                    tx.create(t)
                store.update(create)
                live.append(tid)
            elif op < 0.7:
                tid = rng.choice(live)

                def bump(tx, tid=tid):
                    t = tx.get_task(tid)
                    if t is not None:
                        t = t.copy()
                        t.status.state = TaskState.RUNNING
                        tx.update(t)
                store.update(bump)
            elif op < 0.9:
                tid = live.pop(rng.randrange(len(live)))
                store.update(lambda tx, tid=tid: tx.delete(Task, tid))
            else:
                # reconnect: worker re-registers, gets a fresh COMPLETE
                sid = d.register("n1")
                session = d._sessions["n1"]
                apply_msg(d._full_assignment(session))
            apply_msg(d._incremental(session))

        expected = {
            t.id: t.meta.version.index
            for t in store.view(lambda tx: tx.find_tasks())
            if t.node_id == "n1"
        }
        assert shadow == expected
    finally:
        d.stop()
