"""Rolling-update orders (update/updater.go:367-451): start-first keeps the
replica count at or above desired throughout; stop-first drains a slot
before replacing it."""
import threading
import time

import pytest

from swarmkit_tpu.agent.agent import Agent
from swarmkit_tpu.agent.testutils import FakeExecutor
from swarmkit_tpu.api.specs import Annotations, ContainerSpec, ServiceSpec, TaskSpec, UpdateConfig
from swarmkit_tpu.api.types import TaskState, UpdateOrder
from swarmkit_tpu.manager.manager import Manager
from swarmkit_tpu.store import by

from test_scheduler import wait_for  # noqa: E402


@pytest.fixture
def cluster():
    m = Manager(heartbeat_period=0.5, key_rotation_interval=3600.0)
    m.start()
    agents = []
    for i in range(2):
        ex = FakeExecutor({"*": {"run_forever": True}}, hostname=f"w{i}")
        a = Agent(f"w{i}", m.dispatcher, ex)
        a.start()
        agents.append(a)
    yield m
    for a in agents:
        a.stop()
    m.stop()


def _running(m, svc_id):
    tasks = m.store.view(lambda tx: tx.find_tasks(by.ByServiceID(svc_id)))
    return [t for t in tasks if t.status.state == TaskState.RUNNING
            and t.desired_state <= TaskState.RUNNING]


def _make_service(m, name, order, replicas=4):
    spec = ServiceSpec(
        annotations=Annotations(name=name),
        replicas=replicas,
        task=TaskSpec(runtime=ContainerSpec(image="img:v1")),
        update=UpdateConfig(parallelism=2, delay=0.0, monitor=0.2,
                            order=order),
    )
    spec.spec_version_bump = True
    return m.control_api.create_service(spec)


def _trigger_update(m, svc):
    cur = m.control_api.get_service(svc.id)
    new_spec = cur.spec
    new_spec.task.runtime.image = "img:v2"
    return m.control_api.update_service(svc.id, cur.meta.version, new_spec)


def test_start_first_never_dips_below_desired(cluster):
    m = cluster
    svc = _make_service(m, "sf", UpdateOrder.START_FIRST, replicas=4)
    assert wait_for(lambda: len(_running(m, svc.id)) == 4, timeout=15)

    # sample the live replica count continuously during the update
    low_water = [4]
    stop = threading.Event()

    def sampler():
        while not stop.is_set():
            low_water[0] = min(low_water[0], len(_running(m, svc.id)))
            time.sleep(0.01)

    t = threading.Thread(target=sampler, daemon=True)
    t.start()
    _trigger_update(m, svc)

    def updated():
        tasks = [x for x in m.store.view(
            lambda tx: tx.find_tasks(by.ByServiceID(svc.id)))
            if x.desired_state <= TaskState.RUNNING]
        return (len(tasks) == 4
                and all(x.spec.runtime.image == "img:v2" for x in tasks)
                and all(x.status.state == TaskState.RUNNING for x in tasks))

    assert wait_for(updated, timeout=30)
    stop.set()
    t.join(timeout=2)
    assert low_water[0] >= 4, f"replicas dipped to {low_water[0]}"


def test_stop_first_replaces_all_slots(cluster):
    m = cluster
    svc = _make_service(m, "spf", UpdateOrder.STOP_FIRST, replicas=4)
    assert wait_for(lambda: len(_running(m, svc.id)) == 4, timeout=15)
    _trigger_update(m, svc)

    def updated():
        tasks = [x for x in m.store.view(
            lambda tx: tx.find_tasks(by.ByServiceID(svc.id)))
            if x.desired_state <= TaskState.RUNNING]
        return (len(tasks) == 4
                and all(x.spec.runtime.image == "img:v2" for x in tasks)
                and all(x.status.state == TaskState.RUNNING for x in tasks))

    assert wait_for(updated, timeout=30)
