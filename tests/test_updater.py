"""Rolling-update orders (update/updater.go:367-451): start-first keeps the
replica count at or above desired throughout; stop-first drains a slot
before replacing it."""
import threading
import time

import pytest

from swarmkit_tpu.agent.agent import Agent
from swarmkit_tpu.agent.testutils import FakeExecutor
from swarmkit_tpu.api.specs import Annotations, ContainerSpec, ServiceSpec, TaskSpec, UpdateConfig
from swarmkit_tpu.api.types import TaskState, UpdateOrder
from swarmkit_tpu.manager.manager import Manager
from swarmkit_tpu.store import by

from test_scheduler import wait_for  # noqa: E402


@pytest.fixture
def cluster():
    m = Manager(heartbeat_period=0.5, key_rotation_interval=3600.0)
    m.start()
    agents = []
    for i in range(2):
        ex = FakeExecutor({"*": {"run_forever": True}}, hostname=f"w{i}")
        a = Agent(f"w{i}", m.dispatcher, ex)
        a.start()
        agents.append(a)
    yield m
    for a in agents:
        a.stop()
    m.stop()


def _running(m, svc_id):
    tasks = m.store.view(lambda tx: tx.find_tasks(by.ByServiceID(svc_id)))
    return [t for t in tasks if t.status.state == TaskState.RUNNING
            and t.desired_state <= TaskState.RUNNING]


def _make_service(m, name, order, replicas=4):
    spec = ServiceSpec(
        annotations=Annotations(name=name),
        replicas=replicas,
        task=TaskSpec(runtime=ContainerSpec(image="img:v1")),
        update=UpdateConfig(parallelism=2, delay=0.0, monitor=0.2,
                            order=order),
    )
    spec.spec_version_bump = True
    return m.control_api.create_service(spec)


def _trigger_update(m, svc):
    cur = m.control_api.get_service(svc.id)
    new_spec = cur.spec
    new_spec.task.runtime.image = "img:v2"
    return m.control_api.update_service(svc.id, cur.meta.version, new_spec)


def test_start_first_never_dips_below_desired(cluster):
    m = cluster
    svc = _make_service(m, "sf", UpdateOrder.START_FIRST, replicas=4)
    assert wait_for(lambda: len(_running(m, svc.id)) == 4, timeout=15)

    # sample the live replica count continuously during the update
    low_water = [4]
    stop = threading.Event()

    def sampler():
        while not stop.is_set():
            low_water[0] = min(low_water[0], len(_running(m, svc.id)))
            time.sleep(0.01)

    t = threading.Thread(target=sampler, daemon=True)
    t.start()
    _trigger_update(m, svc)

    def updated():
        tasks = [x for x in m.store.view(
            lambda tx: tx.find_tasks(by.ByServiceID(svc.id)))
            if x.desired_state <= TaskState.RUNNING]
        return (len(tasks) == 4
                and all(x.spec.runtime.image == "img:v2" for x in tasks)
                and all(x.status.state == TaskState.RUNNING for x in tasks))

    assert wait_for(updated, timeout=30)
    stop.set()
    t.join(timeout=2)
    assert low_water[0] >= 4, f"replicas dipped to {low_water[0]}"


def test_stop_first_replaces_all_slots(cluster):
    m = cluster
    svc = _make_service(m, "spf", UpdateOrder.STOP_FIRST, replicas=4)
    assert wait_for(lambda: len(_running(m, svc.id)) == 4, timeout=15)
    _trigger_update(m, svc)

    def updated():
        tasks = [x for x in m.store.view(
            lambda tx: tx.find_tasks(by.ByServiceID(svc.id)))
            if x.desired_state <= TaskState.RUNNING]
        return (len(tasks) == 4
                and all(x.spec.runtime.image == "img:v2" for x in tasks)
                and all(x.status.state == TaskState.RUNNING for x in tasks))

    assert wait_for(updated, timeout=30)


class _SlotWedgingExecutor(FakeExecutor):
    """Wedges ONE slot's v2 replacement in PREPARING forever; everything
    else runs normally."""

    def __init__(self, wedge_slot: int, hostname="wedge-host"):
        super().__init__({"*": {"run_forever": True}}, hostname=hostname)
        self.wedge_slot = wedge_slot

    def controller(self, task):
        from swarmkit_tpu.agent.testutils import FakeController

        if task.slot == self.wedge_slot and \
                task.spec.runtime.image == "img:v2":
            c = FakeController(task, {"prepare_time": 600,
                                      "run_forever": True})
            with self._lock:
                self.controllers.append(c)
            return c
        return super().controller(task)


def test_wedged_start_first_slot_does_not_stall_update(monkeypatch):
    """Round-2 verdict #7: one hung start-first replacement must occupy
    one pool worker — the other slots keep rolling — and when its
    per-slot deadline expires it counts as a FAILURE, so the configured
    policy (pause) fires instead of the update blocking on the wedge."""
    from swarmkit_tpu.api.types import UpdateFailureAction
    from swarmkit_tpu.orchestrator.updater import Updater

    monkeypatch.setattr(Updater, "START_FIRST_TIMEOUT", 10.0)

    m = Manager(heartbeat_period=0.5, key_rotation_interval=3600.0)
    m.start()
    agents = []
    try:
        for i in range(2):
            ex = _SlotWedgingExecutor(wedge_slot=1, hostname=f"ww{i}")
            a = Agent(f"ww{i}", m.dispatcher, ex)
            a.start()
            agents.append(a)

        spec = ServiceSpec(
            annotations=Annotations(name="wedge"),
            replicas=4,
            task=TaskSpec(runtime=ContainerSpec(image="img:v1")),
            update=UpdateConfig(parallelism=2, delay=0.0, monitor=0.3,
                                order=UpdateOrder.START_FIRST,
                                failure_action=UpdateFailureAction.PAUSE,
                                max_failure_ratio=0.0),
        )
        svc = m.control_api.create_service(spec)
        assert wait_for(lambda: len(_running(m, svc.id)) == 4, timeout=20)

        _trigger_update(m, svc)

        def v2_running():
            return [t for t in _running(m, svc.id)
                    if t.spec.runtime.image == "img:v2"]

        # the three healthy slots must flip WELL before the wedged slot's
        # 10s deadline — with the old batch-join, slots 3/4 could not
        # flip until the wedged batch joined at >=10s
        assert wait_for(lambda: len(v2_running()) >= 3, timeout=8), \
            f"only {len(v2_running())} slots flipped before the wedge " \
            "deadline: the update stalled behind the wedged slot"

        # the wedged slot's deadline expires -> failure -> policy: PAUSED
        def paused():
            s = m.control_api.get_service(svc.id)
            return (s.update_status or {}).get("state") == "paused"
        assert wait_for(paused, timeout=45)

        # start-first kept the old v1 task alive in the wedged slot
        v1 = [t for t in _running(m, svc.id)
              if t.spec.runtime.image == "img:v1"]
        assert any(t.slot == 1 for t in v1), \
            "wedged slot lost its old task"
        # and the wedged replacement was removed, not left to pile up
        tasks = m.store.view(lambda tx: tx.find_tasks(by.ByServiceID(svc.id)))
        wedged_v2 = [t for t in tasks if t.slot == 1
                     and t.spec.runtime.image == "img:v2"
                     and t.desired_state < TaskState.SHUTDOWN]
        assert not wedged_v2, "wedged replacement still desired-running"
    finally:
        for a in agents:
            a.stop()
        m.stop()


def test_failed_update_rolls_back_and_reports_rollback_status():
    """failure_action=rollback: the spec flips back to v1 and the status
    walks rollback_started -> rollback_completed (updater.go:566-626)."""
    from swarmkit_tpu.api.types import UpdateFailureAction

    behaviors = {}
    m = Manager(heartbeat_period=0.5, key_rotation_interval=3600.0)
    m.start()
    agents = []
    try:
        for i in range(2):
            ex = FakeExecutor(behaviors, hostname=f"rb{i}")
            a = Agent(f"rb{i}", m.dispatcher, ex)
            a.start()
            agents.append(a)

        from swarmkit_tpu.api.specs import RestartPolicy

        spec = ServiceSpec(
            annotations=Annotations(name="rollme"),
            replicas=3,
            # tiny restart delay: the compiled 5 s default paces every
            # failed-v2 generation and the post-rollback reconverge,
            # multiplying the test's wall clock for no extra coverage
            task=TaskSpec(runtime=ContainerSpec(image="img:v1"),
                          restart=RestartPolicy(delay=0.05)),
            update=UpdateConfig(parallelism=1, delay=0.0, monitor=1.0,
                                order=UpdateOrder.STOP_FIRST,
                                failure_action=UpdateFailureAction.ROLLBACK,
                                max_failure_ratio=0.0),
        )
        svc = m.control_api.create_service(spec)
        behaviors[svc.id] = {"run_forever": True}
        assert wait_for(lambda: len(_running(m, svc.id)) == 3, timeout=20)

        # v2 tasks die instantly: controller exits nonzero
        def exec_for_task(task):
            pass
        # FakeExecutor picks behavior by service id; make v2 fail by
        # switching the service behavior when the update starts
        behaviors[svc.id] = {"exit_code": 1, "run_time": 0.05}
        _trigger_update(m, svc)

        def status():
            s = m.control_api.get_service(svc.id)
            return (s.update_status or {}).get("state")

        assert wait_for(lambda: status() in ("rollback_started",
                                             "rollback_completed"),
                        timeout=30), status()
        # the rollback converges back to v1 running everywhere
        behaviors[svc.id] = {"run_forever": True}

        def rolled_back():
            s = m.control_api.get_service(svc.id)
            run = _running(m, svc.id)
            # convergence of surplus slots is the orchestrator's long
            # tail; the properties under test: spec flipped back, v2 is
            # gone, v1 serves, and the status family is rollback_*
            return (s.spec.task.runtime.image == "img:v1"
                    and len(run) >= 3
                    and all(t.spec.runtime.image == "img:v1" for t in run)
                    and status() == "rollback_completed")
        assert wait_for(rolled_back, timeout=45), \
            (status(), len(_running(m, svc.id)))
    finally:
        for a in agents:
            a.stop()
        m.stop()
