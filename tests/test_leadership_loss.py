"""Leader-only component threads must treat demotion as clean shutdown.

Round-1 verdict weak #2: a RoleManager reconcile racing leadership loss
crashed its thread with ProposeError("not leader; leader is None") and the
suite still passed (pytest only warns on unhandled thread exceptions).
Now (a) conftest turns those warnings into failures suite-wide, and (b)
these tests pin the demotion-tolerant behavior: components stop cleanly
on LeadershipLost/NotLeader and retry on transient ProposeError.

Reference behavior: components exit cleanly on leadership loss
(manager/manager.go:1149+).
"""
import threading
import time

import pytest

from swarmkit_tpu.api.objects import Cluster, Node
from swarmkit_tpu.api.specs import Annotations, ClusterSpec
from swarmkit_tpu.api.types import NodeRole
from swarmkit_tpu.manager.keymanager import KeyManager
from swarmkit_tpu.manager.rolemanager import RoleManager
from swarmkit_tpu.orchestrator.base import EventLoopComponent
from swarmkit_tpu.raft.proposer import LeadershipLost, ProposeError
from swarmkit_tpu.store.memory import MemoryStore
from swarmkit_tpu.utils.leadership import leader_write, leadership_lost


class DemotableStore:
    """MemoryStore proxy whose writes start failing like a demoted
    leader's raft proposer."""

    def __init__(self):
        self._store = MemoryStore()
        self.mode = "leader"  # leader | demoted | flaky

    def __getattr__(self, name):
        return getattr(self._store, name)

    def update(self, cb):
        if self.mode == "demoted":
            raise LeadershipLost("not leader; leader is None")
        if self.mode == "flaky":
            raise ProposeError("proposal timed out")
        return self._store.update(cb)


def _mk_demotion_node(store, node_id="mgr-2"):
    def txn(tx):
        n = tx.get_node(node_id)
        n = n.copy() if n is not None else Node(id=node_id)
        n.role = NodeRole.MANAGER
        n.spec.desired_role = NodeRole.WORKER
        if tx.get_node(node_id) is None:
            tx.create(n)
        else:
            tx.update(n)

    store._store.update(txn)  # seed through the real store


def test_exception_classification():
    assert leadership_lost(LeadershipLost("not leader; leader is None"))
    assert leadership_lost(LeadershipLost("leadership lost"))
    assert not leadership_lost(ProposeError("proposal timed out"))
    assert not leadership_lost(ValueError("boom"))
    from swarmkit_tpu.raft.node import NotLeader

    assert leadership_lost(NotLeader("stepped down"))


def test_leader_write_returns_false_on_demotion():
    store = DemotableStore()
    assert leader_write(store, lambda tx: None, "t") is True
    store.mode = "demoted"
    assert leader_write(store, lambda tx: None, "t") is False
    store.mode = "flaky"
    try:
        leader_write(store, lambda tx: None, "t")
        raise AssertionError("transient error must propagate")
    except ProposeError:
        pass


def test_rolemanager_stops_cleanly_when_demoted_mid_reconcile():
    store = DemotableStore()
    _mk_demotion_node(store)
    store.mode = "demoted"

    rm = RoleManager(store, reconcile_interval=0.05)
    rm.start()
    # the initial reconcile hits the demoted store; the thread must end
    # cleanly (no unhandled exception — conftest fails the test otherwise)
    rm._thread.join(timeout=5)
    assert not rm._thread.is_alive()
    rm.stop()


def test_rolemanager_retries_on_transient_propose_failure():
    store = DemotableStore()
    _mk_demotion_node(store)
    store.mode = "flaky"

    rm = RoleManager(store, reconcile_interval=0.05)
    rm.start()
    try:
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and "mgr-2" not in rm._pending:
            time.sleep(0.02)
        assert "mgr-2" in rm._pending  # queued for retry, thread alive
        assert rm._thread.is_alive()
        # leadership returns: the retry completes the demotion
        store.mode = "leader"
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            n = store.view(lambda tx: tx.get_node("mgr-2"))
            if n.role == NodeRole.WORKER:
                break
            time.sleep(0.02)
        assert store.view(
            lambda tx: tx.get_node("mgr-2")).role == NodeRole.WORKER
    finally:
        rm.stop()


def test_keymanager_stops_cleanly_when_demoted():
    store = DemotableStore()
    store._store.update(lambda tx: tx.create(Cluster(
        id="c1", spec=ClusterSpec(annotations=Annotations(name="default")))))
    km = KeyManager(store, "c1", rotation_interval=0.05)
    km.start()  # seeds keys while leader
    try:
        store.mode = "demoted"
        km._thread.join(timeout=5)
        assert not km._thread.is_alive()
    finally:
        km.stop()


class _WriterComponent(EventLoopComponent):
    name = "writer-under-test"

    def __init__(self, store):
        super().__init__(store)
        self.handled = threading.Event()

    def handle(self, event):
        self.handled.set()
        self.store.update(lambda tx: None)


def test_event_loop_component_stops_on_leadership_loss():
    store = DemotableStore()
    comp = _WriterComponent(store)
    comp.start()
    try:
        store.mode = "demoted"
        # any event now drives a failing write
        store._store.update(lambda tx: tx.create(Node(id="n1")))
        assert comp.handled.wait(timeout=5)
        comp._thread.join(timeout=5)
        assert not comp._thread.is_alive()
    finally:
        comp.stop()


def test_leadership_burst_demote_reelect_restarts_components():
    """A notify(False)+notify(True) burst collapsed to just True used to
    skip the follower/leader cycle entirely; with components now
    self-terminating on LeadershipLost, that left a believing-it-leads
    manager with dead component threads. The buried demote must force a
    full stop/start cycle."""
    # the full Manager assembly needs real certificates; on crypto-less
    # containers this module now COLLECTS (manager/__init__ gained the
    # ca-package crypto gate in ISSUE 15) and only this test skips
    pytest.importorskip("cryptography")
    from swarmkit_tpu.manager.manager import Manager

    mgr = Manager(store=MemoryStore(), org="test-org")
    mgr.start()
    try:
        assert mgr._is_leader
        before = list(mgr._leader_components)
        assert before

        # both transitions sit in the queue before the loop wakes: the
        # collapse path is taken deterministically
        mgr._leadership_q.put(False)
        mgr._leadership_q.put(True)
        t = threading.Thread(target=mgr._leadership_loop, daemon=True)
        t.start()

        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            after = list(mgr._leader_components)
            if after and all(a is not b for a, b in zip(after, before[3:])):
                break
            time.sleep(0.05)
        after = list(mgr._leader_components)
        assert after, "manager lost its components after re-election"
        # per-leadership instances (allocator, scheduler, …) must be FRESH
        fresh = [c for c in after if all(c is not b for b in before)]
        assert fresh, "no component was restarted: burst collapse swallowed " \
                      "the demote"
        assert mgr._is_leader
        mgr._leadership_q.put(None)
        t.join(timeout=5)
    finally:
        mgr.stop()


def test_event_loop_component_survives_transient_failure():
    store = DemotableStore()
    comp = _WriterComponent(store)
    comp.start()
    try:
        store.mode = "flaky"
        store._store.update(lambda tx: tx.create(Node(id="n1")))
        assert comp.handled.wait(timeout=5)
        time.sleep(0.2)
        assert comp._thread.is_alive()  # logged, kept running
    finally:
        comp.stop()
