"""Node bootstrap + remotes tests (reference model: node/node.go flows,
integration/integration_test.go node scenarios, remotes/remotes_test.go)."""
import random

import pytest

from swarmkit_tpu.agent.testutils import FakeExecutor
from swarmkit_tpu.api.types import NodeRole, TaskState
from swarmkit_tpu.api.specs import Annotations, ServiceSpec
from swarmkit_tpu.node import Node, NodeError
from swarmkit_tpu.remotes import ConnectionBroker, Remotes
from swarmkit_tpu.remotes.remotes import NoPeersError
from swarmkit_tpu.store import by

from test_scheduler import wait_for


# -- Remotes / ConnectionBroker ----------------------------------------------


def test_remotes_weighted_selection():
    r = Remotes("m1", "m2", rng=random.Random(7))
    for _ in range(20):
        r.observe("m1", 10)   # healthy
        r.observe("m2", -10)  # failing
    counts = {"m1": 0, "m2": 0}
    for _ in range(300):
        counts[r.select()] += 1
    assert counts["m1"] > counts["m2"] * 2
    # failing peer remains selectable (recovery probe)
    assert counts["m2"] > 0

    assert r.select("m1") == "m2"  # exclusion
    r.remove("m2")
    with pytest.raises(NoPeersError):
        r.select("m1")


def test_connection_broker_prefers_local():
    broker = ConnectionBroker(Remotes("remote-1"))
    conn = broker.select_conn()
    assert conn.peer == "remote-1" and not conn.is_local
    conn.close(success=False)  # observation recorded, no crash

    broker.set_local_peer("local-mgr")
    conn = broker.select_conn()
    assert conn.peer == "local-mgr" and conn.is_local


# -- Node bootstrap ----------------------------------------------------------


def _first_node(tmp_path, name="boot"):
    ex = FakeExecutor({"*": {"run_forever": True}}, hostname=name)
    n = Node(str(tmp_path / name), ex, heartbeat_period=0.5)
    n.start()
    return n


def test_first_node_bootstraps_cluster(tmp_path):
    n = _first_node(tmp_path)
    try:
        assert n.role == NodeRole.MANAGER
        assert n.manager is not None and n.manager.is_leader
        # its own node object is registered and READY
        obj = n.manager.store.view(lambda tx: tx.get_node(n.node_id))
        assert obj is not None and obj.role == NodeRole.MANAGER

        # cluster works: a service reaches RUNNING on the bootstrap node
        svc = n.manager.control_api.create_service(
            ServiceSpec(annotations=Annotations(name="a"), replicas=2)
        )
        assert wait_for(
            lambda: sum(
                1
                for t in n.manager.store.view().find_tasks(by.ByServiceID(svc.id))
                if t.status.state == TaskState.RUNNING
            )
            == 2,
            timeout=15,
        )
    finally:
        n.stop()


def test_worker_join_with_token(tmp_path):
    boot = _first_node(tmp_path)
    try:
        cluster = boot.manager.store.view(
            lambda tx: tx.get_cluster(boot.manager.cluster_id)
        )
        token = cluster.root_ca.join_token_worker

        ex = FakeExecutor({"*": {"run_forever": True}}, hostname="w1")
        w = Node(str(tmp_path / "w1"), ex, join=boot.manager, join_token=token,
                 heartbeat_period=0.5)
        w.start()
        try:
            assert w.role == NodeRole.WORKER
            # the manager sees the worker; dispatcher registration makes it READY
            assert wait_for(
                lambda: (
                    lambda o: o is not None and o.status.state.name == "READY"
                )(boot.manager.store.view(lambda tx: tx.get_node(w.node_id))),
                timeout=10,
            )
            # tasks land on both nodes
            svc = boot.manager.control_api.create_service(
                ServiceSpec(annotations=Annotations(name="b"), replicas=6)
            )
            assert wait_for(
                lambda: sum(
                    1
                    for t in boot.manager.store.view().find_tasks(by.ByServiceID(svc.id))
                    if t.status.state == TaskState.RUNNING
                )
                == 6,
                timeout=20,
            )
            nodes_used = {
                t.node_id
                for t in boot.manager.store.view().find_tasks(by.ByServiceID(svc.id))
            }
            assert w.node_id in nodes_used
        finally:
            w.stop()
    finally:
        boot.stop()


def test_join_requires_token(tmp_path):
    boot = _first_node(tmp_path)
    try:
        ex = FakeExecutor({}, hostname="w1")
        w = Node(str(tmp_path / "w1"), ex, join=boot.manager)
        with pytest.raises(NodeError):
            w.start()
        bad = Node(str(tmp_path / "w2"), FakeExecutor({}, hostname="w2"),
                   join=boot.manager, join_token="SWMTKN-1-bogus-bogus")
        with pytest.raises(Exception):
            bad.start()
    finally:
        boot.stop()


def test_node_identity_survives_restart(tmp_path):
    boot = _first_node(tmp_path)
    try:
        cluster = boot.manager.store.view(
            lambda tx: tx.get_cluster(boot.manager.cluster_id)
        )
        token = cluster.root_ca.join_token_worker
        ex = FakeExecutor({}, hostname="w1")
        w = Node(str(tmp_path / "w1"), ex, join=boot.manager, join_token=token,
                 heartbeat_period=0.5)
        w.start()
        wid = w.node_id
        w.stop()

        # restart from the same state dir, no token needed
        w2 = Node(str(tmp_path / "w1"), FakeExecutor({}, hostname="w1"),
                  join=boot.manager, heartbeat_period=0.5)
        w2.start()
        try:
            assert w2.node_id == wid
        finally:
            w2.stop()
    finally:
        boot.stop()


def test_promotion_starts_embedded_manager(tmp_path):
    boot = _first_node(tmp_path)
    try:
        cluster = boot.manager.store.view(
            lambda tx: tx.get_cluster(boot.manager.cluster_id)
        )
        token = cluster.root_ca.join_token_worker
        ex = FakeExecutor({}, hostname="w1")
        w = Node(str(tmp_path / "w1"), ex, join=boot.manager, join_token=token,
                 heartbeat_period=0.5, role_check_interval=0.05)
        w.start()
        try:
            assert w.manager is None

            def promote(tx):
                obj = tx.get_node(w.node_id)
                obj.spec.desired_role = NodeRole.MANAGER
                tx.update(obj)

            boot.manager.store.update(promote)
            # role manager reconciles cert role; node watcher brings up manager
            assert wait_for(lambda: w.manager is not None, timeout=10)
            assert wait_for(lambda: w.role == NodeRole.MANAGER, timeout=10)

            # demotion tears it down
            def demote(tx):
                obj = tx.get_node(w.node_id)
                obj.spec.desired_role = NodeRole.WORKER
                tx.update(obj)

            boot.manager.store.update(demote)
            assert wait_for(lambda: w.manager is None, timeout=10)
        finally:
            w.stop()
    finally:
        boot.stop()
