"""Placement parity: the JAX water-fill kernel must emit bit-identical
placements to the CPU greedy oracle over randomized cluster states, and the
encoded static mask must agree with the string-based filter pipeline."""
import random

import numpy as np
import pytest

from swarmkit_tpu.api.objects import Node, Task
from swarmkit_tpu.api.specs import (
    Annotations,
    EndpointSpec,
    NodeDescription,
    Placement,
    Platform,
    PortConfig,
    Resources,
)
from swarmkit_tpu.api.types import NodeAvailability, NodeStatusState, TaskState
from swarmkit_tpu.scheduler import batch
from swarmkit_tpu.scheduler.encode import (
    CPU_QUANTUM,
    MEM_QUANTUM,
    TaskGroup,
    encode,
)
from swarmkit_tpu.scheduler.filters import Pipeline
from swarmkit_tpu.scheduler.nodeinfo import NodeInfo

# tier-1 NO_NATIVE coverage (ISSUE 6): every test runs under both the C
# hostops and the pure-Python fallback
pytestmark = pytest.mark.usefixtures("native_walk_mode")

LABEL_KEYS = ["zone", "disk", "tier"]
LABEL_VALS = ["a", "b", "c", "ssd", "hdd"]


def random_node(rng, i):
    n = Node(id=f"node-{i:04d}")
    n.status.state = (NodeStatusState.READY if rng.random() < 0.9
                      else NodeStatusState.DOWN)
    n.status.addr = f"10.0.{i % 256}.{(i * 7) % 256}"
    n.spec.availability = (NodeAvailability.ACTIVE if rng.random() < 0.9
                           else NodeAvailability.DRAIN)
    n.spec.annotations = Annotations(name=f"node-{i}", labels={
        k: rng.choice(LABEL_VALS) for k in LABEL_KEYS if rng.random() < 0.7
    })
    n.description = NodeDescription(
        hostname=f"host-{i}",
        platform=Platform(os=rng.choice(["linux", "windows"]),
                          architecture=rng.choice(["x86_64", "amd64", "arm64"])),
        resources=Resources(
            nano_cpus=rng.randint(1, 16) * CPU_QUANTUM * 1000,
            memory_bytes=rng.randint(1, 64) * MEM_QUANTUM * 1024,
        ),
        plugins=[("Volume", "local"), ("Network", "overlay")]
        + ([("Volume", "nfs")] if rng.random() < 0.5 else []),
    )
    return n


def random_group(rng, gi, n_tasks):
    svc = f"svc-{gi:03d}"
    tasks = []
    for ti in range(n_tasks):
        t = Task(id=f"task-{gi:03d}-{ti:05d}", service_id=svc, slot=ti + 1)
        t.desired_state = TaskState.RUNNING
        tasks.append(t)
    spec = tasks[0].spec
    spec.resources.reservations.nano_cpus = rng.randint(0, 3) * CPU_QUANTUM
    spec.resources.reservations.memory_bytes = rng.randint(0, 4) * MEM_QUANTUM
    choices = []
    if rng.random() < 0.5:
        choices.append(f"node.labels.{rng.choice(LABEL_KEYS)} "
                       f"{'==' if rng.random() < 0.7 else '!='} "
                       f"{rng.choice(LABEL_VALS)}")
    if rng.random() < 0.2:
        choices.append("node.platform.os == linux")
    if rng.random() < 0.1:
        choices.append("node.ip != 10.0.3.0/24")
    spec.placement = Placement(constraints=choices)
    if rng.random() < 0.5:
        from swarmkit_tpu.api.specs import PlacementPreference

        prefs = [PlacementPreference(
            spread_descriptor=f"node.labels.{rng.choice(LABEL_KEYS)}")]
        if rng.random() < 0.4:
            prefs.append(PlacementPreference(
                spread_descriptor=f"node.labels.{rng.choice(LABEL_KEYS)}"))
        spec.placement.preferences = prefs
    if rng.random() < 0.3:
        spec.placement.platforms = [Platform(os="linux", architecture="x86_64")]
    if rng.random() < 0.2:
        spec.placement.max_replicas = rng.randint(1, 3)
    if rng.random() < 0.2:
        for t in tasks:
            t.endpoint = EndpointSpec(ports=[PortConfig(
                protocol="tcp", target_port=80,
                published_port=8000 + gi, publish_mode="host")])
    for t in tasks[1:]:
        t.spec = tasks[0].spec
    return TaskGroup(service_id=svc, spec_version=1, tasks=tasks)


def random_cluster(rng, n_nodes=20, n_groups=5, max_tasks=30):
    infos = []
    for i in range(n_nodes):
        node = random_node(rng, i)
        avail = node.description.resources.copy()
        info = NodeInfo.new(node, {}, avail)
        # pre-existing load, incl. per-service counts (spread-tree totals)
        info.active_tasks_count = rng.randint(0, 5)
        for gi in range(n_groups):
            if rng.random() < 0.3:
                info.active_tasks_count_by_service[f"svc-{gi:03d}"] = \
                    rng.randint(1, 4)
        infos.append(info)
    groups = [random_group(rng, gi, rng.randint(1, max_tasks))
              for gi in range(n_groups)]
    return infos, groups


@pytest.mark.parametrize("seed", range(8))
def test_kernel_matches_cpu_oracle(seed):
    rng = random.Random(seed)
    infos, groups = random_cluster(rng)
    p = encode(infos, groups)
    cpu_counts = batch.cpu_schedule_encoded(p)
    tpu_counts = batch.tpu_schedule_encoded(p)
    np.testing.assert_array_equal(cpu_counts, tpu_counts)
    # per-group totals: every task placed or capacity exhausted
    for gi in range(len(groups)):
        assert cpu_counts[gi].sum() <= p.n_tasks[gi]


def test_materialize_deterministic_and_consistent():
    rng = random.Random(123)
    infos, groups = random_cluster(rng)
    p = encode(infos, groups)
    counts = batch.cpu_schedule_encoded(p)
    a1 = batch.materialize(p, counts)
    a2 = batch.materialize(p, batch.tpu_schedule_encoded(p))
    assert a1 == a2
    # every assigned node was eligible
    mask = batch.cpu_static_mask(p)
    node_idx = {nid: i for i, nid in enumerate(p.node_ids)}
    gi_of = {t.id: gi for gi, g in enumerate(groups) for t in g.tasks}
    for tid, nid in a1.items():
        assert mask[gi_of[tid], node_idx[nid]]


@pytest.mark.parametrize("seed", range(4))
def test_materialize_matches_slot_order_oracle(seed):
    """The vectorized materialize must reproduce the per-slot heap oracle
    (spread.slot_order) exactly, including sequential svc/total carry-over
    between groups."""
    from swarmkit_tpu.scheduler.spread import GroupFill, slot_order

    rng = random.Random(1000 + seed)
    infos, groups = random_cluster(rng)
    p = encode(infos, groups)
    counts = batch.cpu_schedule_encoded(p)

    expected = {}
    totals = p.total0.astype(np.int64).copy()
    svc_counts = p.svc_count0.astype(np.int64).copy()
    for gi, group in enumerate(p.groups):
        c = counts[gi]
        g = GroupFill(
            n_tasks=int(p.n_tasks[gi]),
            eligible=[True] * len(p.node_ids),
            capacity=c.tolist(),
            penalty=p.penalty[gi].tolist(),
            svc_count=svc_counts[p.svc_idx[gi]].tolist(),
            total_count=totals.tolist(),
        )
        for task, node_i in zip(group.tasks, slot_order(g, c.tolist())):
            expected[task.id] = p.node_ids[node_i]
        totals += c
        svc_counts[p.svc_idx[gi]] += c

    assert batch.materialize(p, counts) == expected


def test_static_mask_matches_string_pipeline():
    """The interned-int mask must agree with the reference-style string
    filter chain (minus the dynamic resource/port/replica filters, which the
    mask excludes by design)."""
    rng = random.Random(99)
    infos, groups = random_cluster(rng, n_nodes=30, n_groups=8)
    # Give nodes unlimited resources so dynamic filters pass trivially
    for info in infos:
        info.available_resources.nano_cpus = 10**15
        info.available_resources.memory_bytes = 10**18
    p = encode(infos, groups)
    mask = batch.cpu_static_mask(p)
    pipeline = Pipeline()
    infos_sorted = sorted(infos, key=lambda i: i.node.id)
    for gi, g in enumerate(sorted(groups, key=lambda g: g.key)):
        pipeline.set_task(g.tasks[0])
        for ni, info in enumerate(infos_sorted):
            expected = pipeline.process(info)
            assert mask[gi, ni] == expected, (
                f"group {g.key} node {info.node.id}: mask={mask[gi, ni]} "
                f"pipeline={expected}")


def test_max_replicas_respected():
    rng = random.Random(5)
    infos, groups = random_cluster(rng, n_nodes=5, n_groups=1, max_tasks=40)
    g = groups[0]
    g.spec.placement.constraints = []
    g.spec.placement.platforms = []
    g.spec.placement.max_replicas = 2
    for t in g.tasks:
        t.endpoint = None
    p = encode(infos, groups)
    counts = batch.tpu_schedule_encoded(p)
    assert counts.max() <= 2
    np.testing.assert_array_equal(counts, batch.cpu_schedule_encoded(p))


# ---------------------------------------------------------------------------
# ISSUE 19: binpack + topology-aware strategies and the CSI vol-topo mask leg


@pytest.mark.parametrize("seed", range(8))
def test_binpack_kernel_matches_cpu_oracle(seed):
    """Binpack fills must be bit-identical kernel vs CPU greedy oracle,
    over the same randomized clusters as the spread fuzz."""
    rng = random.Random(7000 + seed)
    infos, groups = random_cluster(rng)
    p = encode(infos, groups, strategy="binpack")
    assert p.strategy == "binpack"
    cpu_counts = batch.cpu_schedule_encoded(p)
    tpu_counts = batch.tpu_schedule_encoded(p)
    np.testing.assert_array_equal(cpu_counts, tpu_counts)
    for gi in range(len(groups)):
        assert cpu_counts[gi].sum() <= p.n_tasks[gi]


@pytest.mark.parametrize("seed", range(4))
def test_binpack_greedy_equals_closed_form(seed):
    """binpack_fill (heap greedy) == binpack_reference (sequential
    consumption in initial-key order) — the equivalence the kernel's
    closed form rests on: an assignment strictly improves the assigned
    node's key, so greedy never switches nodes before capacity exhausts."""
    from swarmkit_tpu.scheduler.spread import (
        GroupFill,
        binpack_fill,
        binpack_reference,
    )

    rng = random.Random(7100 + seed)
    n = 24
    for _ in range(25):
        g = GroupFill(
            n_tasks=rng.randint(0, 60),
            eligible=[rng.random() < 0.8 for _ in range(n)],
            capacity=[rng.randint(0, 5) for _ in range(n)],
            penalty=[rng.random() < 0.15 for _ in range(n)],
            svc_count=[rng.randint(0, 4) for _ in range(n)],
            total_count=[rng.randint(0, 6) for _ in range(n)],
        )
        assert binpack_fill(g) == binpack_reference(g)


@pytest.mark.parametrize("seed", range(4))
def test_binpack_materialize_matches_slot_order(seed):
    """Binpack materialization must reproduce the per-slot oracle
    (spread.binpack_slot_order): nodes consumed in initial-key order,
    each node's slots consecutive, with sequential svc/total carry-over
    between groups."""
    from swarmkit_tpu.scheduler.spread import GroupFill, binpack_slot_order

    rng = random.Random(7200 + seed)
    infos, groups = random_cluster(rng)
    p = encode(infos, groups, strategy="binpack")
    counts = batch.cpu_schedule_encoded(p)

    expected = {}
    totals = p.total0.astype(np.int64).copy()
    svc_counts = p.svc_count0.astype(np.int64).copy()
    for gi, group in enumerate(p.groups):
        c = counts[gi]
        g = GroupFill(
            n_tasks=int(p.n_tasks[gi]),
            eligible=[True] * len(p.node_ids),
            capacity=c.tolist(),
            penalty=p.penalty[gi].tolist(),
            svc_count=svc_counts[p.svc_idx[gi]].tolist(),
            total_count=totals.tolist(),
        )
        for task, node_i in zip(group.tasks, binpack_slot_order(g, c.tolist())):
            expected[task.id] = p.node_ids[node_i]
        totals += c
        svc_counts[p.svc_idx[gi]] += c

    assert batch.materialize(p, counts) == expected


@pytest.mark.parametrize("seed", range(6))
def test_topology_strategy_matches_oracle(seed):
    """Topology-aware spread: the configured axis rides as the OUTERMOST
    spread level of every group; kernel and CPU tree oracle must stay
    bit-identical with it active."""
    rng = random.Random(7300 + seed)
    infos, groups = random_cluster(rng)
    p = encode(infos, groups, strategy="topology",
               topology="node.labels.zone")
    # every group carries the topology axis as level 0
    assert p.spread_rank.shape[1] >= 1
    cpu_counts = batch.cpu_schedule_encoded(p)
    tpu_counts = batch.tpu_schedule_encoded(p)
    np.testing.assert_array_equal(cpu_counts, tpu_counts)


def _plain_node(i, labels):
    n = Node(id=f"node-{i:04d}")
    n.status.state = NodeStatusState.READY
    n.spec.availability = NodeAvailability.ACTIVE
    n.spec.annotations = Annotations(name=f"node-{i}", labels=labels)
    n.description = NodeDescription(
        hostname=f"host-{i}",
        platform=Platform(os="linux", architecture="x86_64"),
        resources=Resources(nano_cpus=64 * CPU_QUANTUM * 1000,
                            memory_bytes=256 * MEM_QUANTUM * 1024),
        plugins=[("Volume", "local")],
    )
    return NodeInfo.new(n, {}, n.description.resources.copy())


def test_topology_balances_zones():
    """Semantic pin: with uniform capacity and empty initial load, the
    topology strategy splits a group's replicas evenly across the axis."""
    infos = [_plain_node(i, {"zone": "abc"[i % 3]}) for i in range(9)]
    g = random_group(random.Random(0), 0, 9)
    g.spec.placement = Placement()
    for t in g.tasks:
        t.endpoint = None
    g.spec.resources.reservations.nano_cpus = 0
    g.spec.resources.reservations.memory_bytes = 0
    p = encode(infos, [g], strategy="topology", topology="node.labels.zone")
    counts = batch.tpu_schedule_encoded(p)
    np.testing.assert_array_equal(counts, batch.cpu_schedule_encoded(p))
    per_zone = {}
    for i, c in enumerate(counts[0]):
        per_zone[i % 3] = per_zone.get(i % 3, 0) + int(c)
    assert per_zone == {0: 3, 1: 3, 2: 3}


@pytest.mark.parametrize("seed", range(5))
def test_vol_topo_mask_matches_volume_walk(seed):
    """The kernel's vol-topo mask leg must agree with the CPU
    check_volumes_on_node walk for every (group, node) pair, and kernel
    vs CPU fills must stay bit-identical with CSI volumes active."""
    from swarmkit_tpu.api.objects import Volume
    from swarmkit_tpu.api.specs import (
        ContainerSpec,
        NodeCSIInfo,
        TaskSpec,
        VolumeAccessMode,
        VolumeMount,
        VolumeSpec,
    )
    from swarmkit_tpu.csi import VolumeSet
    from swarmkit_tpu.csi.plugin import VolumeInfo

    rng = random.Random(7400 + seed)
    zones = ["z0", "z1", "z2"]
    infos = []
    for i in range(12):
        info = _plain_node(i, {})
        info.node.description.csi_info["fake-csi"] = NodeCSIInfo(
            plugin_name="fake-csi", node_id=f"csi-{i}",
            accessible_topology={"zone": rng.choice(zones)},
        )
        infos.append(info)

    vs = VolumeSet()
    vol_names = []
    for vi in range(4):
        name = f"vol-{vi}"
        v = Volume(id=f"v{vi}")
        v.spec = VolumeSpec(
            annotations=Annotations(name=name),
            driver="fake-csi",
            access_mode=VolumeAccessMode(scope="multi", sharing="all"),
            availability="active",
        )
        v.volume_info = VolumeInfo(
            volume_id=f"csi-v{vi}",
            accessible_topology=[
                {"zone": z} for z in rng.sample(zones, rng.randint(1, 2))
            ],
        )
        vs.add_or_update_volume(v)
        vol_names.append(name)

    groups = []
    for gi in range(4):
        tasks = []
        srcs = rng.sample(vol_names, rng.randint(1, 2))
        for ti in range(rng.randint(1, 8)):
            t = Task(id=f"task-{gi:03d}-{ti:05d}", service_id=f"svc-{gi:03d}",
                     slot=ti + 1)
            t.desired_state = TaskState.RUNNING
            tasks.append(t)
        tasks[0].spec = TaskSpec(runtime=ContainerSpec(
            mounts=[VolumeMount(source=s, target=f"/data{j}", type="csi")
                    for j, s in enumerate(srcs)]))
        for t in tasks[1:]:
            t.spec = tasks[0].spec
        groups.append(TaskGroup(service_id=f"svc-{gi:03d}", spec_version=1,
                                tasks=tasks))

    p = encode(infos, groups, volume_set=vs)
    assert p.vol_topo_any in (True, False)
    mask = batch.cpu_static_mask(p)
    infos_sorted = sorted(infos, key=lambda i: i.node.id)
    for gi, g in enumerate(sorted(groups, key=lambda g: g.key)):
        for ni, info in enumerate(infos_sorted):
            expected = vs.check_volumes_on_node(info.node, g.tasks[0])
            assert mask[gi, ni] == expected, (
                f"group {g.key} node {info.node.id}: "
                f"mask={bool(mask[gi, ni])} walk={expected}")
    np.testing.assert_array_equal(batch.cpu_schedule_encoded(p),
                                  batch.tpu_schedule_encoded(p))


def test_host_ports_exclusive():
    rng = random.Random(6)
    infos, groups = random_cluster(rng, n_nodes=6, n_groups=2, max_tasks=10)
    for g in groups:
        g.spec.placement = Placement()
        for t in g.tasks:
            t.endpoint = EndpointSpec(ports=[PortConfig(
                protocol="tcp", target_port=80, published_port=8080,
                publish_mode="host")])
    p = encode(infos, groups)
    counts = batch.tpu_schedule_encoded(p)
    np.testing.assert_array_equal(counts, batch.cpu_schedule_encoded(p))
    # both groups publish the same port: a node may host at most one task
    per_node = counts.sum(axis=0)
    assert per_node.max() <= 1
