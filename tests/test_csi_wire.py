"""CSI plugin wire protocol: a REAL out-of-process plugin over a unix
socket (csi/wire.py + cmd/csi_plugin_example.py), driven by the same
VolumeManager / NodeVolumeManager that drive in-process plugins.

Closes the round-1 inventory's last 'partial': the reference speaks CSI
gRPC to plugin sockets with capability discovery; this is that boundary
on the framework's native wire."""
import os
import subprocess
import sys
import time

import pytest

from swarmkit_tpu.agent.csi import NodeVolumeManager, VolumeAssignment
from swarmkit_tpu.csi import PUBLISHED, PluginGetter, VolumeManager
from swarmkit_tpu.csi.wire import RemoteCSIPlugin
from swarmkit_tpu.store.memory import MemoryStore

from test_csi import _csi_task, _volume
from test_scheduler import wait_for

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _spawn_plugin(sock: str, data: str, *extra):
    """Start the example plugin process and wait for its socket."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + ":" + env.get("PYTHONPATH", "")
    env.pop("XLA_FLAGS", None)
    proc = subprocess.Popen(
        [sys.executable, "-m", "swarmkit_tpu.cmd.csi_plugin_example",
         "--socket", sock, "--data-dir", data, *extra],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env, cwd=REPO)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and not os.path.exists(sock):
        assert proc.poll() is None, proc.stdout.read().decode()
        time.sleep(0.05)
    assert os.path.exists(sock), "plugin socket never appeared"
    return proc


@pytest.fixture
def plugin_proc(tmp_path):
    """The example plugin as a REAL child process on a unix socket."""
    sock = str(tmp_path / "plugin.sock")
    data = str(tmp_path / "data")
    proc = _spawn_plugin(sock, data)
    yield sock, data
    proc.kill()
    proc.wait()


def test_handshake_and_capabilities(plugin_proc):
    sock, _data = plugin_proc
    plugin = RemoteCSIPlugin(sock).connect()
    try:
        assert plugin.name == "dir-csi"
        assert plugin.info.vendor_version
        caps = plugin.capabilities
        assert caps.controller and caps.node
        assert caps.controller_publish and caps.stage_unstage
    finally:
        plugin.close()


def test_volume_manager_drives_external_plugin(plugin_proc):
    """The manager-side VolumeManager runs the full volume lifecycle
    against the external process; the volume materializes as a real
    directory and the publish context crosses the wire."""
    sock, data = plugin_proc
    plugin = RemoteCSIPlugin(sock).connect()
    store = MemoryStore()
    vm = VolumeManager(store, PluginGetter({plugin.name: plugin}))
    vm.start()
    try:
        v = _volume("v1", "vol1", driver="dir-csi")
        store.update(lambda tx: tx.create(v))
        assert wait_for(
            lambda: store.view(
                lambda tx: tx.get_volume("v1")).volume_info is not None,
            timeout=10)
        info = store.view(lambda tx: tx.get_volume("v1")).volume_info
        assert info.volume_id == "dir-csi-v1"
        assert os.path.isdir(os.path.join(data, "volumes", "dir-csi-v1"))

        from swarmkit_tpu.api.types import TaskState

        t = _csi_task("t1")
        t.node_id = "n1"
        t.volumes = ["v1"]
        t.status.state = TaskState.ASSIGNED
        store.update(lambda tx: tx.create(t))
        assert wait_for(
            lambda: any(
                s.node_id == "n1" and s.state == PUBLISHED
                for s in store.view(
                    lambda tx: tx.get_volume("v1")).publish_status),
            timeout=10)
        status = store.view(lambda tx: tx.get_volume("v1")).publish_status[0]
        assert status.publish_context.get("path", "").endswith("dir-csi-v1")

        # delete tears the directory down
        def kill_and_delete(tx):
            cur = tx.get_task("t1").copy()
            cur.status.state = TaskState.COMPLETE
            cur.desired_state = TaskState.SHUTDOWN
            tx.update(cur)
        store.update(kill_and_delete)
        assert wait_for(
            lambda: any(
                s.state != PUBLISHED
                for s in store.view(
                    lambda tx: tx.get_volume("v1")).publish_status),
            timeout=10)
        vm.confirm_node_unpublish("v1", "n1")
        assert wait_for(
            lambda: not store.view(
                lambda tx: tx.get_volume("v1")).publish_status, timeout=10)

        def mark_delete(tx):
            cur = tx.get_volume("v1").copy()
            cur.pending_delete = True
            tx.update(cur)
        store.update(mark_delete)
        assert wait_for(
            lambda: store.view(lambda tx: tx.get_volume("v1")) is None,
            timeout=10)
        assert not os.path.isdir(os.path.join(data, "volumes", "dir-csi-v1"))
    finally:
        vm.stop()
        plugin.close()


def test_node_side_publish_creates_real_path(plugin_proc):
    """The agent-side NodeVolumeManager stages/publishes through the wire:
    node_publish creates the symlink, node_unpublish removes it."""
    sock, data = plugin_proc
    plugin = RemoteCSIPlugin(sock).connect()
    published = []
    nvm = NodeVolumeManager(PluginGetter({plugin.name: plugin}),
                            on_unpublished=published.append)
    nvm.start()
    try:
        # materialize the backing volume first (controller side)
        v = _volume("v9", "vol9", driver="dir-csi")
        info = plugin.create_volume(v)
        va = VolumeAssignment(id="v9", volume_id=info.volume_id,
                              driver="dir-csi")
        nvm.add(va)
        link = os.path.join(data, "published", "v9")
        assert wait_for(lambda: os.path.islink(link), timeout=10)
        assert os.path.isdir(os.readlink(link))

        nvm.remove(va)
        assert wait_for(lambda: "v9" in published, timeout=10)
        assert not os.path.islink(link)
    finally:
        nvm.stop()
        plugin.close()


def test_capability_negotiation_no_stage(tmp_path):
    """A plugin without STAGE_UNSTAGE: the adapter skips the stage round
    trips (CSI capability semantics) and publish still works."""
    sock = str(tmp_path / "ns.sock")
    data = str(tmp_path / "ns-data")
    proc = _spawn_plugin(sock, data, "--no-stage")
    try:
        plugin = RemoteCSIPlugin(sock).connect()
        assert not plugin.capabilities.stage_unstage
        # node_stage is a local no-op for an unknown volume: with the
        # capability present this would raise over the wire
        plugin.node_stage(VolumeAssignment(id="x", volume_id="ghost",
                                           driver="dir-csi"))
        # publish of a real volume still round-trips
        v = _volume("v2", "vol2", driver="dir-csi")
        info = plugin.create_volume(v)
        va = VolumeAssignment(id="v2", volume_id=info.volume_id,
                              driver="dir-csi")
        plugin.node_publish(va)
        assert os.path.islink(os.path.join(data, "published", "v2"))
        plugin.close()
    finally:
        proc.kill()
        proc.wait()


def test_plugin_restart_preserves_volumes(plugin_proc, tmp_path):
    """Directory-backed state survives a plugin restart: a new process on
    the same data dir still publishes the old volume."""
    sock, data = plugin_proc
    plugin = RemoteCSIPlugin(sock).connect()
    v = _volume("v5", "vol5", driver="dir-csi")
    info = plugin.create_volume(v)
    plugin.close()

    sock2 = str(tmp_path / "plugin2.sock")
    proc2 = _spawn_plugin(sock2, data)
    try:
        plugin2 = RemoteCSIPlugin(sock2).connect()
        va = VolumeAssignment(id="v5", volume_id=info.volume_id,
                              driver="dir-csi")
        plugin2.node_publish(va)
        assert os.path.islink(os.path.join(data, "published", "v5"))
        plugin2.close()
    finally:
        proc2.kill()
        proc2.wait()
