"""Cluster telemetry rollup plane (ISSUE 15).

Covers the four layers end to end:
  * snapshot codec — associative/commutative merge, JSON round-trip,
    structural size bound;
  * heartbeat piggyback — shard-stored reports, disarmed beat path
    builds NOTHING (the `_RecordAllocGuard` shape), cadence, hostile
    payload bound, graceful-leave retirement;
  * the acceptance: a FakeClock-driven cluster (leader dispatcher +
    5 agent sessions across ≥2 shards) whose cluster families equal the
    SUM of the per-node registries — counters bit-exact, histogram
    buckets exact — and whose silent node goes STALE within 3× its
    heartbeat period, excluded from the merge and listed;
  * the satellite hammer: metric primitives lose zero increments
    across 8 threads.
"""
from __future__ import annotations

import json
import threading
from functools import reduce

from swarmkit_tpu.dispatcher.dispatcher import (
    GRACE_MULTIPLIER,
    Dispatcher,
)
from swarmkit_tpu.dispatcher.heartbeat import stable_shard
from swarmkit_tpu.manager.telemetry import TelemetryAggregator, TimeSeriesRing
from swarmkit_tpu.store.memory import MemoryStore
from swarmkit_tpu.utils import metrics, telemetry
from swarmkit_tpu.utils.clock import FakeClock
from swarmkit_tpu.utils.metrics import (
    Counter,
    CounterDict,
    CounterFamily,
    Histogram,
    empty_snapshot,
    merge_snapshot,
    registry_snapshot,
    snapshot_counter_value,
    snapshot_series_count,
)


def _node_registry(i: int):
    """A standalone per-node registry (families never touch the
    process-global registry — each fake node gets its own)."""
    cf = CounterFamily("swarm_rpc_handled_total", "handled", ("method",))
    cf.inc(("tick",), i + 1)
    cf.inc(("status",), 2 * i)
    h = Histogram("swarm_store_tx_seconds", "tx")
    h.observe(0.001 * (i + 1))
    h.observe(0.2)
    return registry_snapshot(families=[cf], histograms=[h],
                             gauges={"agent_tasks": i,
                                     "tasks_running": 1})


def assert_cluster_equals_sum(merged: dict, parts: list[dict]):
    """Counters bit-exact, histogram bucket vectors/counts exact, sums
    within float dust, gauges exact (the acceptance's equality)."""
    want = reduce(merge_snapshot, parts, empty_snapshot())
    assert merged["counters"] == want["counters"]
    assert merged["gauges"] == want["gauges"]
    assert set(merged["histograms"]) == set(want["histograms"])
    for name, fam in want["histograms"].items():
        got = merged["histograms"][name]
        assert got["buckets"] == fam["buckets"]
        got_series = {tuple(s[0]): s for s in got["series"]}
        for values, counts, total, n in fam["series"]:
            g = got_series[tuple(values)]
            assert g[1] == counts, (name, values)      # bucket-exact
            assert g[3] == n
            assert abs(g[2] - total) < 1e-9


# ------------------------------------------------------------------ codec
def test_merge_snapshot_associative_commutative_and_json_safe():
    parts = [_node_registry(i) for i in range(4)]
    # JSON round-trip is identity-compatible with merging
    parts[1] = json.loads(json.dumps(parts[1]))
    ab = merge_snapshot(merge_snapshot(parts[0], parts[1]), parts[2])
    ba = merge_snapshot(parts[0], merge_snapshot(parts[1], parts[2]))
    assert json.dumps(ab, sort_keys=True) == json.dumps(ba, sort_keys=True)
    com = merge_snapshot(parts[2], merge_snapshot(parts[1], parts[0]))
    assert ab["counters"] == com["counters"]
    assert ab["gauges"] == com["gauges"]
    total = reduce(merge_snapshot, parts, empty_snapshot())
    assert snapshot_counter_value(total, "swarm_rpc_handled_total",
                                  ("tick",)) == sum(i + 1 for i in range(4))
    # merging the empty snapshot is the identity
    assert merge_snapshot(total, empty_snapshot())["counters"] \
        == total["counters"]
    json.dumps(total)   # the whole artifact stays JSON-safe


def test_merge_snapshot_bucket_mismatch_never_mixes_grids():
    a = {"v": 1, "counters": {}, "gauges": {},
         "histograms": {"h": {"labels": [], "help": "", "buckets": [1.0],
                              "series": [[[], [3], 1.5, 3]]}}}
    b = {"v": 1, "counters": {}, "gauges": {},
         "histograms": {"h": {"labels": [], "help": "",
                              "buckets": [1.0, 2.0],
                              "series": [[[], [1, 1], 2.0, 2]]}}}
    out = merge_snapshot(a, b)
    # larger-n series kept, the drop surfaced — never a summed mix of
    # two bucket spaces
    assert out["histograms"]["h"]["series"][0][3] == 3
    assert out["gauges"]["merge_dropped"] == 1
    # a NEW-key series from a mismatched grid must not land raw under
    # the family's bucket header either
    b2 = {"v": 1, "counters": {}, "gauges": {},
          "histograms": {"h": {"labels": ["k"], "help": "",
                               "buckets": [1.0, 2.0],
                               "series": [[["y"], [1, 1], 2.0, 2]]}}}
    out2 = merge_snapshot(a, b2)
    assert all(s[0] != ["y"] for s in out2["histograms"]["h"]["series"])
    assert out2["gauges"]["merge_dropped"] == 1


def test_registry_snapshot_covers_plain_counter_and_series_count():
    c = Counter("swarm_things_total", "things")
    c.inc(7)
    snap = registry_snapshot(families=[c], histograms=[],
                             gauges={"g": 1})
    assert snapshot_counter_value(snap, "swarm_things_total") == 7
    assert snapshot_series_count(snap) == 2   # one series + one gauge


# ------------------------------------------------- piggyback + dispatcher
def test_disarmed_beat_builds_nothing_and_stores_nothing():
    """The disarmed-cost contract: no snapshot construction, no report
    stored, `node_snapshot` returns None — mirroring the lifecycle
    plane's _RecordAllocGuard shape by spying the builder."""
    calls = {"n": 0}
    orig = metrics.registry_snapshot

    def spy(*a, **k):
        calls["n"] += 1
        return orig(*a, **k)

    clock = FakeClock()
    store = MemoryStore()
    d = Dispatcher(store, heartbeat_period=5.0, clock=clock, shards=2)
    try:
        metrics.registry_snapshot = spy
        assert telemetry.node_snapshot() is None
        sid = d.register("n1")
        d.heartbeat("n1", sid)
        assert calls["n"] == 0
        assert d.telemetry_reports() == [{}, {}]
        # armed, the same surfaces produce and store a report
        with telemetry.armed():
            snap = telemetry.node_snapshot()
            assert snap is not None
            d.heartbeat("n1", sid, metrics=snap)
            reports = d.telemetry_reports()
            assert sum(len(r) for r in reports) == 1
        assert calls["n"] == 1
    finally:
        metrics.registry_snapshot = orig
        d._hb_wheel.stop()


def test_report_stored_in_owning_shard_and_bounded():
    clock = FakeClock()
    d = Dispatcher(MemoryStore(), heartbeat_period=5.0, clock=clock,
                   shards=4)
    try:
        with telemetry.armed() as st:
            sid = d.register("nodeA")
            snap = registry_snapshot(families=[], histograms=[],
                                     gauges={"x": 1})
            d.heartbeat("nodeA", sid, metrics=snap)
            reports = d.telemetry_reports()
            owner = stable_shard("nodeA", 4)
            assert set(reports[owner]) == {"nodeA"}
            assert all(not r for i, r in enumerate(reports)
                       if i != owner)
            # hostile payload: structural bound, not a JSON encode
            huge = {"v": 1, "histograms": {}, "gauges": {
                f"g{i}": i for i in range(telemetry.MAX_REPORT_SERIES + 1)},
                "counters": {}}
            d.heartbeat("nodeA", sid, metrics=huge)
            assert st.reports_rejected == 1
            assert d.telemetry_reports()[owner]["nodeA"][0] is snap
            # non-dict garbage is rejected, never raises
            d.heartbeat("nodeA", sid, metrics=[1, 2, 3])
            assert st.reports_rejected == 2
            # ONE series with a huge counts vector must trip the cell
            # budget (series count alone would pass)
            fat = {"v": 1, "counters": {}, "gauges": {},
                   "histograms": {"x": {"labels": [], "buckets": [1.0],
                                        "series": [[[], [0] * 500_000,
                                                    0.0, 0]]}}}
            d.heartbeat("nodeA", sid, metrics=fat)
            assert st.reports_rejected == 3
            assert d.telemetry_reports()[owner]["nodeA"][0] is snap
            # graceful leave retires the report
            d.leave("nodeA", sid)
            assert sum(len(r) for r in d.telemetry_reports()) == 0
    finally:
        d._hb_wheel.stop()


def test_node_snapshot_gauges_and_truncation():
    class FakeWorker:
        _tasks = {"t1": 1, "t2": 2}

    class FakeAgent:
        _pending = {"t1": object()}
        worker = FakeWorker()

    from swarmkit_tpu.utils import lifecycle

    with telemetry.armed():
        with lifecycle.armed() as rec:
            rec.record("t1", "NEW")
            rec.record("t2", "NEW")
            rec.record("t2", "RUNNING")
            snap = telemetry.node_snapshot(agent=FakeAgent())
        g = snap["gauges"]
        assert g["agent_pending_statuses"] == 1
        assert g["agent_tasks"] == 2
        assert g["tasks_new"] == 1
        assert g["tasks_running"] == 1
    # oversize budget degrades to gauges-only, truncated flagged
    with telemetry.armed(max_bytes=10) as st:
        snap = telemetry.node_snapshot(agent=FakeAgent())
        assert snap["truncated"] is True
        assert snap["counters"] == {} and snap["histograms"] == {}
        assert snap["gauges"]["agent_tasks"] == 2
        assert st.reports_truncated == 1


def test_stage_census_shape():
    from swarmkit_tpu.utils.lifecycle import LifecycleRecorder

    r = LifecycleRecorder()
    r.record("a", "NEW")
    r.record("b", "NEW")
    r.record("b", "ASSIGNED")
    assert r.stage_census() == {"NEW": 1, "ASSIGNED": 1}


# ----------------------------------------------------------- acceptance
def test_driven_rollup_parity_and_staleness():
    """THE acceptance: leader dispatcher + 5 agent sessions across ≥2
    shards under FakeClock — cluster families equal the sum of the
    per-node registries (counters bit-exact, buckets exact), and a node
    whose beats stop is STALE within 3× its heartbeat period, listed
    and excluded (never folded into the aggregate silently)."""
    clock = FakeClock()
    store = MemoryStore()
    period = 5.0
    d = Dispatcher(store, heartbeat_period=period, clock=clock, shards=4)
    node_ids = [f"node{i:02d}" for i in range(5)]
    assert len({stable_shard(n, 4) for n in node_ids}) >= 2
    try:
        with telemetry.armed():
            sids = {n: d.register(n) for n in node_ids}
            snaps = {}
            for i, n in enumerate(node_ids):
                snaps[n] = _node_registry(i)
                d.heartbeat(n, sids[n], metrics=snaps[n])
            agg = TelemetryAggregator(store, d, clock=clock)
            roll = agg.rollup(include_local=False)
            assert roll["armed"] is True
            assert roll["nodes"]["reported"] == 5
            assert roll["nodes"]["fresh"] == 5
            assert roll["nodes"]["stale"] == []
            assert_cluster_equals_sum(roll["cluster"],
                                      list(snaps.values()))
            # the exposition renders the summed families
            text = agg.prometheus_text()
            total = sum(i + 1 for i in range(5))
            assert (f'swarm_cluster_rpc_handled_total{{method="tick"}} '
                    f'{total}') in text
            assert "swarm_cluster_store_tx_seconds_bucket" in text
            assert "swarm_cluster_nodes_fresh 5" in text

            # node00 goes silent; everyone else keeps beating
            clock.advance(2 * period)
            for n in node_ids[1:]:
                d.heartbeat(n, sids[n], metrics=snaps[n])
            clock.advance(GRACE_MULTIPLIER * period - 2 * period + 0.5)
            roll2 = agg.rollup(include_local=False)
            assert roll2["nodes"]["stale"] == ["node00"]
            assert roll2["nodes"]["fresh"] == 4
            assert roll2["nodes"]["flaps"] == {"node00": 1}
            # stale data EXCLUDED from the aggregate, not averaged in
            assert_cluster_equals_sum(
                roll2["cluster"],
                [snaps[n] for n in node_ids[1:]])
            text2 = agg.prometheus_text()
            assert "swarm_cluster_nodes_stale 1" in text2
            assert 'swarm_cluster_stale_node_info{node="node00"} 1' \
                in text2
            # every family in the cluster exposition owns a HELP line
            # (the exposition-drift convention)
            assert "# HELP swarm_cluster_stale_node_info" in text2
    finally:
        d._hb_wheel.stop()


def test_rollup_include_local_merges_process_registry():
    clock = FakeClock()
    d = Dispatcher(MemoryStore(), heartbeat_period=5.0, clock=clock,
                   shards=1)
    try:
        with telemetry.armed():
            # a real registry family this process owns
            fam = metrics.counter_family(
                "swarm_telemetry_selftest_total", "selftest", ("k",))
            fam.inc(("x",), 11)
            agg = TelemetryAggregator(MemoryStore(), d, clock=clock)
            roll = agg.rollup(include_local=True)
            assert snapshot_counter_value(
                roll["cluster"], "swarm_telemetry_selftest_total",
                ("x",)) >= 11
            without = agg.rollup(include_local=False)
            assert "swarm_telemetry_selftest_total" \
                not in without["cluster"]["counters"]
    finally:
        d._hb_wheel.stop()


def test_local_registry_not_double_counted_with_colocated_agent():
    """swarmd managers co-run an agent in the SAME process — its
    piggybacked report IS this process's registry, so include_local
    must not merge the registry a second time while that report is
    fresh (and must fall back to the local merge once it goes away)."""
    clock = FakeClock()
    d = Dispatcher(MemoryStore(), heartbeat_period=5.0, clock=clock,
                   shards=2)
    try:
        with telemetry.armed():
            fam = metrics.counter_family(
                "swarm_telemetry_dedupe_total", "dedupe", ("k",))
            fam.inc(("x",), 5)
            base = fam.value(("x",))
            sid = d.register("leader-node")
            # the co-located agent's report: the process registry
            d.heartbeat("leader-node", sid,
                        metrics=metrics.registry_snapshot())
            agg = TelemetryAggregator(MemoryStore(), d, clock=clock,
                                      local_node_id="leader-node")
            roll = agg.rollup(include_local=True)
            assert snapshot_counter_value(
                roll["cluster"], "swarm_telemetry_dedupe_total",
                ("x",)) == base   # once, not twice
            # report gone (graceful leave) -> local registry merges
            d.leave("leader-node", sid)
            roll2 = agg.rollup(include_local=True)
            assert snapshot_counter_value(
                roll2["cluster"], "swarm_telemetry_dedupe_total",
                ("x",)) == base
    finally:
        d._hb_wheel.stop()


def test_control_api_surface_and_aggregator_registration():
    from swarmkit_tpu.controlapi.control import ControlAPI

    clock = FakeClock()
    store = MemoryStore()
    d = Dispatcher(store, heartbeat_period=5.0, clock=clock, shards=1)
    ctl = ControlAPI(store)
    try:
        assert ctl.get_cluster_telemetry() == {"armed": False,
                                               "aggregator": False}
        agg = TelemetryAggregator(store, d, clock=clock)
        agg.start()
        try:
            assert telemetry.aggregator() is agg
            with telemetry.armed():
                out = ctl.get_cluster_telemetry(window=30.0,
                                                include_local=False)
                assert out["armed"] is True
                assert out["window_s"] == 30.0
                assert "windows" in out
        finally:
            agg.stop()
        assert telemetry.aggregator() is None
        # a stale stop never clobbers a newer registration
        agg2 = TelemetryAggregator(store, d, clock=clock)
        agg2.start()
        agg.stop()
        assert telemetry.aggregator() is agg2
        agg2.stop()
    finally:
        d._hb_wheel.stop()


def test_rollup_carries_raft_recovery_counters():
    """ISSUE 18: the manager block of the rollup surfaces the raft
    recovery plane (snapshot chunks sent/resent, suffix resumes,
    installs) whenever the wired raft object maintains it — the
    swarmbench `recovery_plane` block reads exactly these keys."""
    from swarmkit_tpu.raft.node import RaftNode

    clock = FakeClock()
    d = Dispatcher(MemoryStore(), heartbeat_period=5.0, clock=clock,
                   shards=1)
    try:
        raft = RaftNode(raft_id=1, transport=None)
        raft.snap_chunks_sent = 7
        raft.snap_chunks_resent = 3
        raft.snap_resume_suffix = 1
        agg = TelemetryAggregator(MemoryStore(), d, raft=raft,
                                  clock=clock)
        rec = agg.rollup()["manager"]["raft"]["recovery"]
        assert rec["snap_chunks_sent"] == 7
        assert rec["snap_chunks_resent"] == 3
        assert rec["snap_resume_suffix"] == 1
        for key in ("snap_chunks_rejected", "snap_installs",
                    "snap_install_seconds"):
            assert key in rec
    finally:
        d._hb_wheel.stop()


def test_time_series_ring_windows_and_quantiles():
    clock = FakeClock()
    ring = TimeSeriesRing(width_s=1.0, slots=10, clock=clock)
    for i in range(5):
        ring.observe("lat", float(i))
        clock.advance(1.0)
    qs = ring.quantiles("lat", (50, 100))
    assert qs[100] == 4.0
    # trailing-window restriction drops old windows
    recent = ring.samples("lat", window_s=2.0)
    assert set(recent) <= {3.0, 4.0} and recent
    # ring wrap overwrites the oldest windows
    for i in range(20):
        ring.observe("lat", 100.0 + i)
        clock.advance(1.0)
    assert all(v >= 100.0 for v in ring.samples("lat"))


# ------------------------------------------------------ satellite: hammer
def test_counter_primitives_lose_zero_increments_across_threads():
    c = Counter("hammer_total")
    fam = CounterFamily("hammer_family_total", "", ("k",))
    bag = CounterDict({"x": 0})
    h = Histogram("hammer_seconds")
    N, T = 2000, 8

    def worker():
        for _ in range(N):
            c.inc()
            fam.inc(("a",))
            bag.inc("x")
            h.observe(0.001)

    threads = [threading.Thread(target=worker) for _ in range(T)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == N * T
    assert fam.value(("a",)) == N * T
    assert bag["x"] == N * T
    assert h.snapshot()[2] == N * T


def test_agent_piggyback_cadence_in_heartbeat_loop():
    """Drive a real Agent session against an in-process dispatcher:
    armed with report_every=2, beats alternate bare/piggybacked; the
    dispatcher ends up with exactly the piggybacked reports."""
    import time as _time

    from swarmkit_tpu.agent.agent import Agent

    class FakeExecutor:
        def describe(self):
            return None

        def controller(self, task):
            raise NotImplementedError

    store = MemoryStore()
    d = Dispatcher(store, heartbeat_period=0.05, shards=2)
    with telemetry.armed(report_every=2) as st:
        a = Agent("hb-node", d, FakeExecutor())
        a.start()
        try:
            deadline = _time.monotonic() + 5.0
            while _time.monotonic() < deadline \
                    and st.reports_stored == 0:
                _time.sleep(0.02)
            assert st.reports_stored >= 1
            assert st.reports_built == st.reports_stored
            reports = d.telemetry_reports()
            assert sum(len(r) for r in reports) == 1
            (snap, _stamp), = [r["hb-node"] for r in reports
                               if "hb-node" in r]
            assert snap["v"] == 1
        finally:
            a.leave()
            d.stop()


def test_rollup_carries_logbroker_block():
    """ISSUE 20: the manager block of the rollup surfaces the log
    fan-out plane's accounting (published/delivered/shed + plane
    gauges) whenever a broker with a metrics_snapshot surface is wired
    in — `swarmctl top`, /debug/cluster and the swarmbench `log_plane`
    block read exactly this dict."""
    from swarmkit_tpu.api.objects import Task
    from swarmkit_tpu.api.types import TaskState
    from swarmkit_tpu.logbroker import make_log_message
    from swarmkit_tpu.logbroker.broker import LogSelector
    from swarmkit_tpu.logbroker.sharded import ShardedLogBroker

    clock = FakeClock()
    store = MemoryStore()

    def seed(tx):
        t = Task(id="t-roll", service_id="svc-roll", node_id="n-roll")
        t.status.state = TaskState.RUNNING
        tx.create(t)

    store.update(seed)
    d = Dispatcher(MemoryStore(), heartbeat_period=5.0, clock=clock,
                   shards=1)
    try:
        broker = ShardedLogBroker(store, shards=2, client_limit=1)
        sub_id, _client = broker.subscribe_logs(
            LogSelector(service_ids=["svc-roll"]))
        t = store.view(lambda tx: tx.get_task("t-roll"))
        broker.publish_logs(
            sub_id, [make_log_message(t, "stdout", b"a"),
                     make_log_message(t, "stdout", b"b")])   # b sheds
        agg = TelemetryAggregator(MemoryStore(), d, clock=clock,
                                  log_broker=broker)
        lb = agg.rollup()["manager"]["logbroker"]
        assert lb["published"] == 2
        assert lb["delivered"] == 1
        assert lb["shed"] == 1 and lb["shed_windows"] == 1
        assert lb["published"] == lb["delivered"] + lb["shed"]
        assert lb["pending_subscriptions"] == 1
        assert lb["subscriptions_opened"] == 1
        # no broker wired → no block (worker-side aggregators)
        agg2 = TelemetryAggregator(MemoryStore(), d, clock=clock)
        assert "logbroker" not in agg2.rollup()["manager"]
    finally:
        d._hb_wheel.stop()
