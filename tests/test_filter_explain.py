"""Explain parity (ISSUE 19 satellite): per-filter failure counts derived
from the kernel-side encoded arrays (`batch.cpu_filter_explain`) must equal
the CPU filter chain's short-circuit `Pipeline._failures` tally, over mixed
clusters exercising every filter leg — readiness, resources, plugins,
constraints, platforms, host ports, max-replicas, and CSI volume topology.

CHAOS_SEED discipline: every test derives ALL randomness from its seed and
prints `CHAOS_SEED=<n>` on failure so the exact cluster is replayable.

The fuzz deliberately avoids `node.ip` constraints: those ride extra_mask
(host-side residue), which cpu_filter_explain attributes to the volumes
leg — the one documented misattribution."""
import random

import numpy as np
import pytest

from swarmkit_tpu.api.objects import Node, Task, Volume
from swarmkit_tpu.api.specs import (
    Annotations,
    ContainerSpec,
    EndpointSpec,
    NodeCSIInfo,
    NodeDescription,
    Placement,
    Platform,
    PortConfig,
    Resources,
    TaskSpec,
    VolumeAccessMode,
    VolumeMount,
    VolumeSpec,
)
from swarmkit_tpu.api.types import NodeAvailability, NodeStatusState, TaskState
from swarmkit_tpu.csi import VolumeSet
from swarmkit_tpu.csi.plugin import VolumeInfo
from swarmkit_tpu.scheduler import batch
from swarmkit_tpu.scheduler.batch import FILTER_LEGS, cpu_filter_explain
from swarmkit_tpu.scheduler.encode import (
    CPU_QUANTUM,
    MEM_QUANTUM,
    TaskGroup,
    encode,
)
from swarmkit_tpu.scheduler.filters import Pipeline
from swarmkit_tpu.scheduler.nodeinfo import NodeInfo

LEG_BY_FILTER = {
    "ReadyFilter": "ready",
    "ResourceFilter": "resource",
    "PluginFilter": "plugin",
    "ConstraintFilter": "constraint",
    "PlatformFilter": "platform",
    "HostPortFilter": "hostport",
    "MaxReplicasFilter": "max_replicas",
    "VolumesFilter": "volumes",
}

ZONES = ["z0", "z1", "z2"]
LABEL_VALS = ["a", "b", "c"]


def _mixed_cluster(rng, n_nodes=16, n_groups=8):
    """A cluster where every filter leg has a chance to fire: DOWN/DRAIN
    nodes, quantum-multiple reservations vs small nodes, optional nfs
    volume plugin, label constraints (incl. values no node carries),
    platform mixes, pre-used host ports colliding with group publishes,
    preloaded per-service counts vs max-replicas caps, and CSI volumes
    with topology subsets (incl. a zone no node reports)."""
    infos = []
    for i in range(n_nodes):
        n = Node(id=f"node-{i:04d}")
        n.status.state = (NodeStatusState.READY if rng.random() < 0.85
                          else NodeStatusState.DOWN)
        n.spec.availability = (NodeAvailability.ACTIVE if rng.random() < 0.9
                               else NodeAvailability.DRAIN)
        n.spec.annotations = Annotations(name=f"node-{i}", labels=(
            {"zone": rng.choice(LABEL_VALS)} if rng.random() < 0.8 else {}))
        n.description = NodeDescription(
            hostname=f"host-{i}",
            platform=Platform(os=rng.choice(["linux", "windows"]),
                              architecture=rng.choice(["x86_64", "arm64"])),
            resources=Resources(
                nano_cpus=rng.randint(1, 8) * CPU_QUANTUM * 1000,
                memory_bytes=rng.randint(1, 8) * MEM_QUANTUM * 1024,
            ),
            plugins=[("Volume", "local"), ("Network", "overlay")]
            + ([("Volume", "nfs")] if rng.random() < 0.5 else []),
        )
        if rng.random() < 0.7:
            n.description.csi_info["fake-csi"] = NodeCSIInfo(
                plugin_name="fake-csi", node_id=f"csi-{i}",
                accessible_topology={"zone": rng.choice(ZONES)})
        info = NodeInfo.new(n, {}, n.description.resources.copy())
        for gi in range(n_groups):
            if rng.random() < 0.35:
                info.active_tasks_count_by_service[f"svc-{gi:03d}"] = \
                    rng.randint(1, 4)
        if rng.random() < 0.4:
            info.used_host_ports.add(("tcp", 8000 + rng.randint(0, 3)))
        infos.append(info)

    vs = VolumeSet()
    vol_names = []
    for vi in range(3):
        name = f"vol-{vi}"
        v = Volume(id=f"v{vi}")
        v.spec = VolumeSpec(
            annotations=Annotations(name=name),
            driver="fake-csi",
            access_mode=VolumeAccessMode(scope="multi", sharing="all"),
            availability="active",
        )
        topo = ([{"zone": "z9"}] if rng.random() < 0.25 else
                [{"zone": z} for z in rng.sample(ZONES, rng.randint(1, 2))])
        v.volume_info = VolumeInfo(volume_id=f"csi-v{vi}",
                                   accessible_topology=topo)
        vs.add_or_update_volume(v)
        vol_names.append(name)

    groups = []
    for gi in range(n_groups):
        svc = f"svc-{gi:03d}"
        tasks = []
        for ti in range(rng.randint(1, 6)):
            t = Task(id=f"task-{gi:03d}-{ti:05d}", service_id=svc, slot=ti + 1)
            t.desired_state = TaskState.RUNNING
            tasks.append(t)
        mounts = []
        if rng.random() < 0.4:
            for j, s in enumerate(rng.sample(vol_names, rng.randint(1, 2))):
                mounts.append(
                    VolumeMount(source=s, target=f"/data{j}", type="csi"))
        if rng.random() < 0.3:
            mounts.append(
                VolumeMount(source="nfs/share", target="/nfs", type="volume"))
        if mounts:
            tasks[0].spec = TaskSpec(runtime=ContainerSpec(mounts=mounts))
        spec = tasks[0].spec
        # node-scale quantum multiples so the resource leg can actually
        # exceed the smaller nodes (they hold 1-8 of these units)
        spec.resources.reservations.nano_cpus = \
            rng.randint(0, 6) * CPU_QUANTUM * 1000
        spec.resources.reservations.memory_bytes = \
            rng.randint(0, 6) * MEM_QUANTUM * 1024
        cons = []
        if rng.random() < 0.5:
            cons.append(f"node.labels.zone "
                        f"{'==' if rng.random() < 0.7 else '!='} "
                        f"{rng.choice(LABEL_VALS + ['q'])}")
        spec.placement = Placement(constraints=cons)
        if rng.random() < 0.3:
            spec.placement.platforms = [Platform(
                os=rng.choice(["linux", "windows"]), architecture="x86_64")]
        if rng.random() < 0.35:
            spec.placement.max_replicas = rng.randint(1, 3)
        if rng.random() < 0.4:
            for t in tasks:
                t.endpoint = EndpointSpec(ports=[PortConfig(
                    protocol="tcp", target_port=80,
                    published_port=8000 + rng.randint(0, 3),
                    publish_mode="host")])
        for t in tasks[1:]:
            t.spec = tasks[0].spec
        groups.append(TaskGroup(service_id=svc, spec_version=1, tasks=tasks))
    return infos, groups, vs


@pytest.mark.parametrize("seed", range(24))
def test_explain_matches_pipeline(seed):
    """Kernel-side per-filter failure counts == the string Pipeline's
    short-circuit tally, for every group of a mixed cluster."""
    rng = random.Random(9100 + seed)
    try:
        infos, groups, vs = _mixed_cluster(rng)
        p = encode(infos, groups, volume_set=vs)
        counts = cpu_filter_explain(p)
        infos_sorted = sorted(infos, key=lambda i: i.node.id)
        pipe = Pipeline(volume_set=vs)
        for gi, g in enumerate(sorted(groups, key=lambda g: g.key)):
            pipe.set_task(g.tasks[0])
            survivors = sum(pipe.process(info) for info in infos_sorted)
            expect = {LEG_BY_FILTER[type(f).__name__]: c
                      for f, c in pipe._failures.items() if c}
            got = {leg: int(c)
                   for leg, c in zip(FILTER_LEGS, counts[gi]) if c}
            assert got == expect, (
                f"group {g.key}: kernel {got} != pipeline {expect}")
            assert int(counts[gi].sum()) == len(infos_sorted) - survivors
    except AssertionError:
        print(f"CHAOS_SEED={seed}")
        raise


@pytest.mark.parametrize("seed", range(8))
def test_explain_residual_matches_eligibility(seed):
    """Nodes NOT charged to any leg are exactly the statically eligible
    nodes with positive pre-fill dynamic capacity — the population both
    fill engines start from."""
    rng = random.Random(9400 + seed)
    try:
        infos, groups, vs = _mixed_cluster(rng)
        p = encode(infos, groups, volume_set=vs)
        counts = cpu_filter_explain(p)
        eligible = batch.cpu_static_mask(p)
        avail = p.avail_res.astype(np.int64)
        port_used = p.port_used0
        N = eligible.shape[1]
        for gi in range(counts.shape[0]):
            svc = p.svc_count0[p.svc_idx[gi]].astype(np.int64)
            caps = batch._group_caps(p, gi, avail, svc, port_used)
            ok = int((eligible[gi] & (caps > 0)).sum())
            assert int(counts[gi].sum()) == N - ok
    except AssertionError:
        print(f"CHAOS_SEED={seed}")
        raise
