"""Network allocation depth: subnets/gateways, service VIPs, task
attachment addresses, node ingress attachments, release on death, and
idempotent rebuild across allocator restarts (reference
manager/allocator/network.go:448-1132)."""
import ipaddress
import time

import pytest

from swarmkit_tpu.allocator.allocator import Allocator
from swarmkit_tpu.allocator.ipam import IPAM, IPAMError
from swarmkit_tpu.api.objects import Network, Node, Service, Task
from swarmkit_tpu.api.specs import (
    Annotations,
    NetworkAttachmentConfig,
    NetworkSpec,
    PortConfig,
    ServiceSpec,
)
from swarmkit_tpu.api.types import NodeStatusState, TaskState
from swarmkit_tpu.store import by
from swarmkit_tpu.store.memory import MemoryStore

from test_scheduler import wait_for  # noqa: E402


@pytest.fixture
def store():
    return MemoryStore()


def _mk_network(store, net_id="net1", name="backend", ingress=False,
                subnet=None):
    n = Network(id=net_id, spec=NetworkSpec(
        annotations=Annotations(name=name), ingress=ingress,
        ipam={"subnet": subnet} if subnet else None))
    store.update(lambda tx: tx.create(n))
    return n


def _mk_service(store, svc_id="svc1", networks=(), ports=()):
    s = Service(id=svc_id, spec=ServiceSpec(
        annotations=Annotations(name=svc_id), replicas=1))
    s.spec.task.networks = [NetworkAttachmentConfig(target=t)
                            for t in networks]
    s.spec.endpoint.ports = list(ports)
    store.update(lambda tx: tx.create(s))
    return s


def _mk_task(store, tid, svc_id):
    t = Task(id=tid, service_id=svc_id)
    t.status.state = TaskState.NEW
    t.desired_state = TaskState.RUNNING
    store.update(lambda tx: tx.create(t))
    return t


def test_ipam_pools_and_exhaustion():
    ipam = IPAM()
    subnet, gw = ipam.add_network("n1", "192.168.5.0/30")  # 2 hosts: gw + 1
    assert gw == "192.168.5.1"
    a = ipam.allocate("n1")
    assert a == "192.168.5.2"
    with pytest.raises(IPAMError):
        ipam.allocate("n1")
    ipam.release("n1", a)
    assert ipam.allocate("n1") == a

    # auto-assigned subnets never overlap
    s2, _ = ipam.add_network("n2")
    s3, _ = ipam.add_network("n3")
    assert not ipaddress.ip_network(s2).overlaps(ipaddress.ip_network(s3))


def test_restore_tolerates_bad_persisted_subnet(store):
    # a /32 persisted before the subnet-size check existed must not abort
    # the whole IPAM rebuild on the next leadership change
    bad = Network(id="nbad", spec=NetworkSpec(
        annotations=Annotations(name="bad")))
    bad.driver_state = {"subnet": "10.8.0.1/32", "gateway": "10.8.0.1"}
    corrupt = Network(id="ncorrupt", spec=NetworkSpec(
        annotations=Annotations(name="corrupt")))
    corrupt.driver_state = {"subnet": "garbage", "gateway": ""}
    good = Network(id="ngood", spec=NetworkSpec(
        annotations=Annotations(name="good")))
    good.driver_state = {"subnet": "172.21.0.0/24", "gateway": "172.21.0.1"}
    store.update(lambda tx: (tx.create(bad), tx.create(corrupt),
                             tx.create(good)))
    # a service on the GOOD network: its VIP must still be allocated even
    # though earlier networks in the snapshot have unusable subnets
    _mk_service(store, "svcg", networks=("ngood",))
    a = Allocator(store)
    a.start()
    try:
        assert wait_for(lambda: a.ipam.has_network("ngood"), timeout=5)
        assert not a.ipam.has_network("nbad")
        assert not a.ipam.has_network("ncorrupt")

        def vip_allocated():
            s = store.view(lambda tx: tx.get_service("svcg"))
            return s.endpoint and s.endpoint.get("virtual_ips")
        assert wait_for(vip_allocated, timeout=5)
    finally:
        a.stop()


def test_network_gets_subnet_and_gateway(store):
    _mk_network(store, subnet="172.20.0.0/24")
    a = Allocator(store)
    a.start()
    try:
        def allocated():
            n = store.view(lambda tx: tx.get_network("net1"))
            return (n.driver_state or {}).get("subnet") == "172.20.0.0/24" \
                and (n.driver_state or {}).get("gateway") == "172.20.0.1"
        assert wait_for(allocated, timeout=5)
    finally:
        a.stop()


def test_service_vip_and_task_attachments(store):
    _mk_network(store)
    _mk_service(store, networks=["backend"])
    _mk_task(store, "t1", "svc1")
    _mk_task(store, "t2", "svc1")
    a = Allocator(store)
    a.start()
    try:
        def done():
            s = store.view(lambda tx: tx.get_service("svc1"))
            ts = store.view(lambda tx: tx.find_tasks(by.ByServiceID("svc1")))
            return (s.endpoint and s.endpoint.get("virtual_ips")
                    and all(t.status.state == TaskState.PENDING
                            and t.networks for t in ts))
        assert wait_for(done, timeout=5)
        s = store.view(lambda tx: tx.get_service("svc1"))
        [(net_id, vip)] = s.endpoint["virtual_ips"]
        assert net_id == "net1"
        ts = store.view(lambda tx: tx.find_tasks(by.ByServiceID("svc1")))
        addrs = [t.networks[-1]["addresses"][0] for t in ts]
        subnet = ipaddress.ip_network(
            store.view(lambda tx: tx.get_network("net1"))
            .driver_state["subnet"])
        # distinct addresses, all within the subnet, none equal to the VIP
        assert len(set(addrs + [vip])) == 3
        for addr in addrs + [vip]:
            assert ipaddress.ip_address(addr) in subnet
    finally:
        a.stop()


def test_task_waits_for_network_then_allocates(store):
    _mk_service(store, networks=["backend"])
    _mk_task(store, "t1", "svc1")
    a = Allocator(store)
    a.start()
    try:
        time.sleep(0.5)
        t = store.view(lambda tx: tx.get_task("t1"))
        assert t.status.state == TaskState.NEW  # referenced net missing
        _mk_network(store)
        assert wait_for(
            lambda: store.view(lambda tx: tx.get_task("t1")).status.state
            == TaskState.PENDING, timeout=5)
    finally:
        a.stop()


def test_dead_task_returns_addresses(store):
    _mk_network(store, subnet="192.168.9.0/29")  # gw + 5 usable
    _mk_service(store, networks=["backend"])
    _mk_task(store, "t1", "svc1")
    a = Allocator(store)
    a.start()
    try:
        assert wait_for(
            lambda: store.view(lambda tx: tx.get_task("t1")).status.state
            == TaskState.PENDING, timeout=5)
        t = store.view(lambda tx: tx.get_task("t1"))
        addr = t.networks[-1]["addresses"][0]

        def kill(tx):
            cur = tx.get_task("t1").copy()
            cur.status.state = TaskState.FAILED
            tx.update(cur)
        store.update(kill)
        assert wait_for(lambda: addr not in
                        a.ipam._pools["net1"].allocated, timeout=5)
    finally:
        a.stop()


def test_ingress_attachment_for_ready_nodes_and_ingress_vip(store):
    _mk_network(store, net_id="ingress", name="ingress", ingress=True)
    n = Node(id="node1")
    n.status.state = NodeStatusState.READY
    store.update(lambda tx: tx.create(n))
    _mk_service(store, ports=[PortConfig(protocol="tcp", target_port=80,
                                         publish_mode="ingress")])
    a = Allocator(store)
    a.start()
    try:
        def node_attached():
            node = store.view(lambda tx: tx.get_node("node1"))
            return any(att.get("network_id") == "ingress"
                       for att in node.attachments or []
                       if isinstance(att, dict))
        assert wait_for(node_attached, timeout=5)

        def svc_has_ingress_vip():
            s = store.view(lambda tx: tx.get_service("svc1"))
            return s.endpoint and any(
                net_id == "ingress"
                for net_id, _ in s.endpoint.get("virtual_ips", []))
        assert wait_for(svc_has_ingress_vip, timeout=5)
    finally:
        a.stop()


def test_ipam_exhaustion_after_reserve_raises():
    """Exhaustion must raise even when reserved addresses left the cursor
    parked at the wrap target (the leader-failover restore path)."""
    ipam = IPAM()
    ipam.add_network("n1", "192.168.5.0/30")
    ipam.reserve("n1", "192.168.5.2")   # fills the only host slot
    with pytest.raises(IPAMError):
        ipam.allocate("n1")


def test_service_created_before_network_gets_vip_later(store):
    _mk_service(store, networks=["backend"])
    a = Allocator(store)
    a.start()
    try:
        import time as _t
        _t.sleep(0.4)
        s = store.view(lambda tx: tx.get_service("svc1"))
        assert not (s.endpoint or {}).get("virtual_ips")
        _mk_network(store)

        def has_vip():
            s = store.view(lambda tx: tx.get_service("svc1"))
            return bool((s.endpoint or {}).get("virtual_ips"))
        assert wait_for(has_vip, timeout=5)
    finally:
        a.stop()


def test_dnsrr_mode_releases_vips(store):
    _mk_network(store)
    _mk_service(store, networks=["backend"])
    a = Allocator(store)
    a.start()
    try:
        def has_vip():
            s = store.view(lambda tx: tx.get_service("svc1"))
            return bool((s.endpoint or {}).get("virtual_ips"))
        assert wait_for(has_vip, timeout=5)
        vip = dict(store.view(lambda tx: tx.get_service("svc1"))
                   .endpoint["virtual_ips"])["net1"]

        def flip(tx):
            s = tx.get_service("svc1").copy()
            s.spec.endpoint.mode = "dnsrr"
            tx.update(s)
        store.update(flip)

        assert wait_for(lambda: not has_vip(), timeout=5)
        assert vip not in a.ipam._pools["net1"].allocated
    finally:
        a.stop()


def test_restart_rebuilds_without_double_assignment(store):
    _mk_network(store)
    _mk_service(store, networks=["backend"])
    _mk_task(store, "t1", "svc1")
    a = Allocator(store)
    a.start()
    try:
        assert wait_for(
            lambda: store.view(lambda tx: tx.get_task("t1")).status.state
            == TaskState.PENDING, timeout=5)
    finally:
        a.stop()
    s = store.view(lambda tx: tx.get_service("svc1"))
    vip = dict(s.endpoint["virtual_ips"])["net1"]
    taken = store.view(
        lambda tx: tx.get_task("t1")).networks[-1]["addresses"][0]

    # a fresh allocator (leadership change) must not hand out vip/taken again
    b = Allocator(store)
    b.start()
    try:
        _mk_task(store, "t2", "svc1")
        assert wait_for(
            lambda: store.view(lambda tx: tx.get_task("t2")).status.state
            == TaskState.PENDING, timeout=5)
        addr2 = store.view(
            lambda tx: tx.get_task("t2")).networks[-1]["addresses"][0]
        assert addr2 not in (vip, taken)
        # service keeps its original VIP
        s2 = store.view(lambda tx: tx.get_service("svc1"))
        assert dict(s2.endpoint["virtual_ips"])["net1"] == vip
    finally:
        b.stop()
