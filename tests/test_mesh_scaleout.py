"""Mesh scale-out plane invariants (ISSUE 7).

Pins the safety rules that make sharded resident ticks sound at scale:

  * the tick jit's donation set is EXACTLY the 8 STATE arrays — donating
    any group-table position would hand the kernel invalidated buffers
    on a group-cache hit;
  * `_MESH_TICKS` stays per-Mesh (a per-instance jit wrapper would
    discard the compile cache on every scheduler restart);
  * a steady mesh tick pays 0 device_put/reshard of the carry and
    O(delta) H2D bytes (op-count guarded via counters + a device_put
    spy), with the carry's out_shardings pinned across ticks;
  * the group-table cache's identity gate (encoder spread-table reuse +
    placeholder singletons) short-circuits the padded rebuild;
  * the sampled-shard parity methodology (parallel/shard_parity.py)
    agrees with the FULL oracle at sizes where both can run, and its
    invariant checker actually rejects corrupted fills.
"""
import logging
import random

import numpy as np
import pytest

import jax

from swarmkit_tpu.models.cluster_step import example_cluster, synth_shard_cluster
from swarmkit_tpu.ops import resident as res_mod
from swarmkit_tpu.ops.raft_replay import replay_commit
from swarmkit_tpu.parallel.mesh import (
    make_mesh,
    mesh_context,
    resident_shardings,
    shard_problem,
    sharded_cluster_step,
    sharded_schedule,
)
from swarmkit_tpu.parallel.shard_parity import (
    check_fill_invariants,
    sampled_shard_parity,
)
from swarmkit_tpu.scheduler import batch
from swarmkit_tpu.scheduler.encode import IncrementalEncoder, encode


def test_mesh_context_compat_usable():
    """The set_mesh/use_mesh/Mesh-ctx fallback chain must yield a working
    context manager on THIS jax (the seed failed here outright)."""
    mesh = make_mesh(8)
    with mesh_context(mesh):
        pass


def test_mesh_tick_jit_donates_exactly_the_state_arrays(monkeypatch):
    mesh = make_mesh(8)
    res_mod._MESH_TICKS.pop(mesh, None)
    calls = []
    real_jit = jax.jit

    def spy_jit(fn, *a, **kw):
        calls.append(dict(kw))
        return real_jit(fn, *a, **kw)

    monkeypatch.setattr(res_mod.jax, "jit", spy_jit)
    res_mod._mesh_ticks(mesh, resident_shardings(mesh))
    donating = [kw for kw in calls if "donate_argnums" in kw]
    assert len(donating) == 1, "exactly one donating tick jit per mesh"
    assert tuple(donating[0]["donate_argnums"]) \
        == tuple(range(len(res_mod.STATE_FIELDS))) \
        == res_mod.DONATE_STATE_ARGNUMS, \
        "donation set must be exactly the 8 STATE arrays — never a " \
        "group-table position (the group cache reuses those buffers)"
    assert all("out_shardings" in kw for kw in calls), \
        "mesh tick jits must pin out_shardings (carry never resharded)"


def test_mesh_ticks_cached_per_mesh_not_per_instance():
    mesh = make_mesh(8)
    rp1 = res_mod.ResidentPlacement(IncrementalEncoder(), mesh=mesh)
    n_cached = len(res_mod._MESH_TICKS)
    rp2 = res_mod.ResidentPlacement(IncrementalEncoder(), mesh=mesh)
    assert rp1._tick_donating is rp2._tick_donating
    assert rp1._tick_plain is rp2._tick_plain
    assert len(res_mod._MESH_TICKS) == n_cached


def _two_waves(n_nodes=131, n_groups=3, tasks_per_group=24):
    """Two waves of the SAME services: identical specs, fresh task ids."""
    infos, w0 = example_cluster(n_nodes=n_nodes, n_groups=n_groups,
                                tasks_per_group=tasks_per_group)
    _, w1 = example_cluster(n_nodes=n_nodes, n_groups=n_groups,
                            tasks_per_group=tasks_per_group)
    for g in w1:
        for t in g.tasks:
            t.id = "w1-" + t.id
        g.ids = [t.id for t in g.tasks]
    return infos, w0, w1


def _commit_wave(enc, rp, infos, p, counts):
    """Oracle-parity check + the apply_counts contract (one add_task per
    placed task), so the next encode sees zero dirty rows."""
    np.testing.assert_array_equal(counts, batch.cpu_schedule_encoded(p))
    assignments = batch.materialize(p, counts)
    by_node = {i.node.id: i for i in infos}
    task_by_id = {t.id: t for g in p.groups for t in g.tasks}
    for tid, nid in assignments.items():
        assert by_node[nid].add_task(task_by_id[tid])
    assert enc.apply_counts(p, counts)
    rp.after_apply(p, counts)


def test_steady_mesh_tick_opcount_guard(monkeypatch):
    """The judged steady-tick contract on the mesh backend: zero full
    re-uploads, zero carry device_puts/reshards, O(delta) H2D bytes, and
    0 group-table ships when nothing group-side moved."""
    mesh = make_mesh(8)
    enc = IncrementalEncoder()
    rp = res_mod.ResidentPlacement(enc, mesh=mesh)
    infos, w0, w1 = _two_waves()

    p0 = enc.encode(infos, w0)
    counts0 = rp.schedule(p0)
    _commit_wave(enc, rp, infos, p0, counts0)
    assert rp.uploads_full == 1

    p1 = enc.encode(infos, w1)
    assert enc.last_dirty == 0, "steady wave must find zero dirty rows"

    puts = []
    real_put = jax.device_put

    def spy_put(x, *a, **kw):
        puts.append(x)
        return real_put(x, *a, **kw)

    monkeypatch.setattr(res_mod.jax, "device_put", spy_put)
    full0, gt0, b0 = rp.uploads_full, rp.uploads_group_tables, \
        rp.uploads_h2d_bytes
    counts1 = rp.schedule(p1)
    monkeypatch.setattr(res_mod.jax, "device_put", real_put)
    _commit_wave(enc, rp, infos, p1, counts1)

    assert rp.uploads_full == full0, "steady tick re-uploaded the carry"
    assert rp.uploads_group_tables == gt0, \
        "steady wave of identical services re-shipped group tables"
    # ONE batched device_put of the placeholder delta rows only
    assert len(puts) == 1
    shipped = puts[0] if isinstance(puts[0], list) else [puts[0]]
    h2d = sum(np.asarray(a).nbytes for a in shipped)
    assert h2d == rp.uploads_h2d_bytes - b0
    # O(delta) with delta == 0: placeholder rows only — far below even ONE
    # real node column, let alone the [S, N] service matrix
    assert h2d < len(p1.node_ids) * 4, \
        f"steady tick shipped {h2d} bytes (expected O(delta)=placeholders)"
    # pinned carry layout: every state array still carries the declared
    # NamedSharding — GSPMD never resharded/replicated the carry
    for f, arr in zip(res_mod.STATE_FIELDS, rp._state):
        assert arr.sharding == rp._shard[f], \
            f"carry array {f} left its pinned sharding"


def test_group_table_identity_gate_and_spread_cache():
    """The encoder re-emits an unchanged spread table as the SAME object
    (identity-stable), and the resident cache turns that into an O(1)
    hit; a full-dirty row invalidates the cached ranks."""
    infos, w0, w1 = _two_waves()
    enc = IncrementalEncoder()
    p0 = enc.encode(infos, w0)
    assert p0.spread_rank.shape[1] >= 1
    p1 = enc.encode(infos, w1)
    assert p1.spread_rank is p0.spread_rank, \
        "steady encode rebuilt the spread table"
    # flags stamped: no penalties, nothing host-masked in this cluster
    assert p1.penalty_nonzero is False
    assert p1.extra_mask_all in (True, False)

    # a replaced node object (full string re-encode) must invalidate
    from swarmkit_tpu.scheduler.nodeinfo import NodeInfo
    old = infos[0]
    infos[0] = NodeInfo.new(old.node, dict(old.tasks),
                            old.available_resources.copy())
    p2 = enc.encode(infos, w0)
    assert p2.spread_rank is not p1.spread_rank, \
        "label-dirty encode reused stale spread ranks"
    np.testing.assert_array_equal(np.asarray(p2.spread_rank),
                                  np.asarray(p1.spread_rank))


def test_placeholder_singletons_are_identity_stable():
    infos, w0, w1 = _two_waves()
    enc = IncrementalEncoder()
    mesh = make_mesh(8)
    rp = res_mod.ResidentPlacement(enc, mesh=mesh)
    p0 = enc.encode(infos, w0)
    counts0 = rp.schedule(p0)
    assert rp._gsrc[7] is res_mod._PLACEHOLDER_FALSE      # penalty off
    assert rp._gsrc[12] is res_mod._PLACEHOLDER_VOLTOPO   # no CSI volumes
    _commit_wave(enc, rp, infos, p0, counts0)
    p1 = enc.encode(infos, w1)
    gt0 = rp.uploads_group_tables
    counts1 = rp.schedule(p1)
    assert rp.uploads_group_tables == gt0, \
        "placeholder slots must identity-hit, not re-ship"
    _commit_wave(enc, rp, infos, p1, counts1)


def _csi_cluster(n_nodes=24, n_groups=3, tasks_per_group=8):
    """CSI cluster for the vol-topo mask leg: nodes in 3 zones, one
    volume accessible from z0/z2, every group mounting it."""
    import sys
    sys.path.insert(0, "tests")
    from test_placement_parity import _plain_node

    from swarmkit_tpu.api.objects import Task, Volume
    from swarmkit_tpu.api.specs import (
        Annotations,
        ContainerSpec,
        NodeCSIInfo,
        TaskSpec,
        VolumeAccessMode,
        VolumeMount,
        VolumeSpec,
    )
    from swarmkit_tpu.api.types import TaskState
    from swarmkit_tpu.csi import VolumeSet
    from swarmkit_tpu.csi.plugin import VolumeInfo
    from swarmkit_tpu.scheduler.encode import TaskGroup

    infos = []
    for i in range(n_nodes):
        info = _plain_node(i, {})
        info.node.description.csi_info["fake-csi"] = NodeCSIInfo(
            plugin_name="fake-csi", node_id=f"csi-{i}",
            accessible_topology={"zone": f"z{i % 3}"})
        infos.append(info)
    vs = VolumeSet()
    v = Volume(id="v0")
    v.spec = VolumeSpec(
        annotations=Annotations(name="vol-0"), driver="fake-csi",
        access_mode=VolumeAccessMode(scope="multi", sharing="all"),
        availability="active")
    v.volume_info = VolumeInfo(
        volume_id="csi-v0",
        accessible_topology=[{"zone": "z0"}, {"zone": "z2"}])
    vs.add_or_update_volume(v)

    def wave(tag):
        groups = []
        for gi in range(n_groups):
            tasks = []
            for ti in range(tasks_per_group):
                t = Task(id=f"{tag}-task-{gi:03d}-{ti:05d}",
                         service_id=f"svc-{gi:03d}", slot=ti + 1)
                t.desired_state = TaskState.RUNNING
                tasks.append(t)
            tasks[0].spec = TaskSpec(runtime=ContainerSpec(
                mounts=[VolumeMount(source="vol-0", target="/data",
                                    type="csi")]))
            for t in tasks[1:]:
                t.spec = tasks[0].spec
            groups.append(TaskGroup(service_id=f"svc-{gi:03d}",
                                    spec_version=1, tasks=tasks))
        return groups

    return infos, vs, wave("w0"), wave("w1")


def test_voltopo_tables_identity_stable_and_cached():
    """ISSUE 19: the encoder re-emits unchanged CSI vol-topo rows as the
    SAME object, the resident group cache identity-hits them (0 ships on
    the steady wave), and kernel/oracle parity holds with the leg live."""
    mesh = make_mesh(8)
    infos, vs, w0, w1 = _csi_cluster()
    enc = IncrementalEncoder()
    rp = res_mod.ResidentPlacement(enc, mesh=mesh)
    p0 = enc.encode(infos, w0, volume_set=vs)
    assert p0.vol_topo_any is True and p0.vol_topo.shape[1] > 0
    counts0 = rp.schedule(p0)
    _commit_wave(enc, rp, infos, p0, counts0)
    assert rp._gsrc[12] is p0.vol_topo

    p1 = enc.encode(infos, w1, volume_set=vs)
    assert p1.vol_topo is p0.vol_topo, \
        "steady encode rebuilt the vol-topo table"
    full0, gt0 = rp.uploads_full, rp.uploads_group_tables
    counts1 = rp.schedule(p1)
    assert rp.uploads_full == full0, "steady tick re-uploaded the carry"
    assert rp.uploads_group_tables == gt0, \
        "identity-stable vol-topo rows re-shipped"
    _commit_wave(enc, rp, infos, p1, counts1)
    # placements confined to the volume's zones (z0/z2 — node i is z{i%3})
    placed = np.flatnonzero(counts1.sum(axis=0) > 0)
    assert placed.size > 0 and not (placed % 3 == 1).any(), \
        "a task placed in a zone the volume cannot reach"


def test_binpack_mesh_steady_tick_opcount():
    """ISSUE 19: the binpack strategy rides the same steady-tick
    machinery — identity-stable group tables, zero re-uploads on the
    steady wave, oracle parity via the strategy-aware dispatch."""
    mesh = make_mesh(8)
    enc = IncrementalEncoder(strategy="binpack")
    rp = res_mod.ResidentPlacement(enc, mesh=mesh)
    infos, w0, w1 = _two_waves()
    p0 = enc.encode(infos, w0)
    assert p0.strategy == "binpack"
    counts0 = rp.schedule(p0)
    _commit_wave(enc, rp, infos, p0, counts0)
    p1 = enc.encode(infos, w1)
    full0, gt0 = rp.uploads_full, rp.uploads_group_tables
    counts1 = rp.schedule(p1)
    assert (rp.uploads_full, rp.uploads_group_tables) == (full0, gt0), \
        "binpack steady wave broke the zero-ship contract"
    _commit_wave(enc, rp, infos, p1, counts1)


@pytest.mark.parametrize("strategy", ["binpack", "topology"])
def test_synth_strategy_sampled_parity(strategy):
    """The sampled-shard oracle is strategy-aware: binpack and topology
    fills on the shard-partitioned grid match the sliced CPU oracle on
    every shard, and the grown invariant sweep stays green."""
    p, gshard = synth_shard_cluster(8 * 32, 8, groups_per_shard=2,
                                    tasks_per_group=100, seed=3, lmax=2,
                                    strategy=strategy)
    assert p.strategy == strategy
    mesh = make_mesh(8)
    counts = sharded_schedule(p, mesh)
    np.testing.assert_array_equal(counts, batch.cpu_schedule_encoded(p))
    checked = sampled_shard_parity(p, counts, gshard, 8, list(range(8)))
    assert checked == list(range(8))
    check_fill_invariants(p, counts)


def test_chunked_shard_problem_matches_plain():
    rng = random.Random(3)
    import sys
    sys.path.insert(0, "tests")
    from test_placement_parity import random_cluster

    infos, groups = random_cluster(rng, n_nodes=53, n_groups=4)
    p = encode(infos, groups)
    mesh = make_mesh(8)
    plain, N = shard_problem(p, mesh)
    stats = {}
    chunked, N2 = shard_problem(p, mesh, stats=stats, chunked=1)
    assert N == N2 and stats["h2d_bytes"] > 0
    for a, b in zip(plain, chunked):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert a.sharding == b.sharding
    counts = sharded_schedule(p, mesh)
    np.testing.assert_array_equal(counts, batch.cpu_schedule_encoded(p))


def test_synth_shard_cluster_sampled_parity_agrees_with_full_oracle():
    """The methodology's own validation: at a size where the FULL oracle
    still runs, the sampled-shard oracle must agree with it on every
    shard — proving the slice restriction is bit-exact, not approximate."""
    mesh = make_mesh(8)
    p, gshard = synth_shard_cluster(8 * 64, 8, groups_per_shard=2,
                                    tasks_per_group=300, seed=7, lmax=2)
    managers, log_len = 5, 2048
    acks = np.zeros((managers, log_len), bool)
    fr = np.random.RandomState(5).randint(100, log_len, managers)
    for m in range(managers):
        acks[m, :fr[m]] = True
    stats = {}
    counts, commit = sharded_cluster_step(p, acks, np.int32(3), mesh,
                                          stats=stats)
    assert commit == int(replay_commit(acks, 3)[0])
    # full oracle parity (feasible at this size)
    np.testing.assert_array_equal(counts, batch.cpu_schedule_encoded(p))
    # sampled-shard parity on EVERY shard + the invariant sweep
    checked = sampled_shard_parity(p, counts, gshard, 8, list(range(8)))
    assert checked == list(range(8))
    info = check_fill_invariants(p, counts)
    assert 0 < info["placed"] <= info["tasks"]
    assert stats["h2d_bytes"] > 0 and stats["fill_s"] > 0


def test_invariant_checker_rejects_corrupt_fills():
    p, gshard = synth_shard_cluster(8 * 16, 8, groups_per_shard=1,
                                    tasks_per_group=40, seed=1, lmax=1)
    mesh = make_mesh(8)
    counts = sharded_schedule(p, mesh)
    check_fill_invariants(p, counts)

    bad = counts.copy()
    bad[0, np.flatnonzero(gshard != 0)[0] * 16] += 1  # wrong shard's node
    with pytest.raises(AssertionError):
        check_fill_invariants(p, bad)
    with pytest.raises(AssertionError):
        sampled_shard_parity(p, bad, gshard, 8, [int(gshard[0])])

    bad2 = counts.copy()
    bad2[0] += 10_000          # overcommit + conservation violation
    with pytest.raises(AssertionError):
        check_fill_invariants(p, bad2)


def test_scheduler_mesh_backend_logs_chosen_devices(caplog):
    from swarmkit_tpu.scheduler.scheduler import Scheduler
    from swarmkit_tpu.store.memory import MemoryStore

    sched = Scheduler(MemoryStore(), backend="mesh", mesh=6)
    with caplog.at_level(logging.INFO, logger="swarmkit_tpu.scheduler"):
        mesh = sched._make_mesh()
    assert mesh.devices.size == 4, "6 devices must round down to 4"
    assert any("using 4 of 6" in r.message for r in caplog.records), \
        "mesh backend must log the rounded-down device count"
