"""Recovery-at-scale plane (ISSUE 18 tentpole cap): seeded recovery
storm + resumable-snapshot pins.

The storm drives the three recovery legs the plane is judged on, on one
storage-backed cluster with real MemoryStore snapshot payloads (so the
columnar fast-restore path is exercised end to end):

  A. leader kill      — isolate the leader, fake-clock time until a new
                        quorum-reachable leader is signalled;
  B. ENOSPC lift      — WAL fsync ENOSPC degrades the leader to a
                        read-only follower; time until the probe lifts
                        the degradation AND the cluster commits again;
  C. lagging catch-up — a member isolated past compaction catches up
                        via the resumable chunk stream under seeded
                        chunk loss; time until applied == leader commit.

Per-leg durations (fake seconds — the harness clock is the shared
FakeClock, so every sample is seed-deterministic) feed the same
`--slo`-style gate swarmbench uses (utils/slo.evaluate_samples). ALL
randomness derives from the seed; a failure prints CHAOS_SEED=<n> and
re-running that parametrized seed replays the exact storm
(docs/fault_injection.md contract). Fast seeds ride tier-1; the soak is
`-m chaos` (nightly).

The pins below the storm hold the resumable-stream protocol itself:
suffix-only resend (chunk-count op guard), FakeClock-driven pause TTL,
ack-progress deadline re-arm, reassembly-buffer caps/eviction, the
install crash window (truncate-before-save ordering), and a ≥20-seed
chunk loss/dup/reorder fuzz asserting installed-state byte-identity
with a clean transfer.
"""
import random
from contextlib import contextmanager

import pytest

from swarmkit_tpu.api.objects import Node, Service, Task
from swarmkit_tpu.api.specs import Annotations, ServiceSpec
from swarmkit_tpu.api.types import NodeStatusState, TaskState
from swarmkit_tpu.raft.messages import SnapshotChunk
from swarmkit_tpu.raft.node import SNAPSHOT_CHUNK_BYTES, SNAPSHOT_RESEND_TICKS
from swarmkit_tpu.raft.storage import RaftStorage
from swarmkit_tpu.raft.testutils import RaftCluster
from swarmkit_tpu.rpc import codec
from swarmkit_tpu.store.columnar import ColumnarTasks
from swarmkit_tpu.store.memory import MemoryStore
from swarmkit_tpu.utils import failpoints
from swarmkit_tpu.utils import slo as slo_mod

FAST_SEEDS = list(range(2))
SOAK_SEEDS = list(range(2, 10))

# enough payload for a multi-chunk stream without bloating the fast tier
_BLOB_CHUNKS = 4


@contextmanager
def chaos_seed(seed):
    try:
        yield
    except BaseException:
        print(f"\nCHAOS_SEED={seed}")
        raise


def _seed_store(tag, n_nodes=4, n_tasks=24, pad_chunks=_BLOB_CHUNKS):
    """A store whose snapshot is big enough to stream in several chunks
    (the padding rides a service label through the ordinary codec)."""
    store = MemoryStore()

    def seed(tx):
        for i in range(n_nodes):
            n = Node(id=f"{tag}-n{i:02d}")
            n.status.state = NodeStatusState.READY
            tx.create(n)
        svc = Service(id=f"{tag}-svc")
        svc.spec = ServiceSpec(
            annotations=Annotations(
                name=f"{tag}-svc",
                labels={"pad": "x" * (pad_chunks * SNAPSHOT_CHUNK_BYTES)}),
            replicas=3)
        tx.create(svc)
        for i in range(n_tasks):
            t = Task(id=f"{tag}-t{i:03d}", service_id=f"{tag}-svc",
                     slot=i + 1)
            t.status.state = TaskState.PENDING
            t.desired_state = TaskState.RUNNING
            tx.create(t)

    store.update(seed)
    return store


def _mk_cluster(tmp_path, tag, n=3, snapshot_interval=12, seed=7,
                pad_chunks=_BLOB_CHUNKS):
    """Storage-backed cluster whose snapshot payloads are REAL MemoryStore
    saves — install on a follower goes through MemoryStore.restore and
    therefore the columnar adoption path."""
    storages = {i: RaftStorage(str(tmp_path / f"{tag}-r{i}"))
                for i in range(1, n + 1)}
    c = RaftCluster(n, storages=storages, seed=seed,
                    snapshot_interval=snapshot_interval)
    stores = {}
    for i, node in c.nodes.items():
        st = _seed_store(tag, pad_chunks=pad_chunks)
        node.snapshot_state = st.save
        node.restore_state = st.restore
        stores[i] = st
    return c, stores, storages


def _columnar_matches_rebuild(store):
    tasks = store.view(lambda tx: tx.find_tasks())
    services = store.view(lambda tx: tx.find_services())
    nodes = store.view(lambda tx: tx.find_nodes())
    secrets = store.view(lambda tx: tx.find_secrets())
    configs = store.view(lambda tx: tx.find_configs())
    rebuilt = ColumnarTasks.rebuild(tasks, services=services, nodes=nodes,
                                    secrets=secrets, configs=configs)
    return ColumnarTasks.snapshots_equal(store.columnar.snapshot(),
                                         rebuilt.snapshot())


# ------------------------------------------------------------------ storm
def run_recovery_storm(seed, tmp_path, churn=20, slo_arg="p50:30.0,p99:90.0"):
    """One seeded storm; returns the SLO report dict (for the gate)."""
    rng = random.Random(seed)
    c, stores, _storages = _mk_cluster(tmp_path, f"s{seed}",
                                       snapshot_interval=12, seed=seed)
    samples = []
    leader = c.elect(rng.randint(1, 3))
    for k in range(5):
        assert c.propose({"op": "warm", "k": k})

    # ---- leg A: leader kill -------------------------------------------
    t0 = c.clock.monotonic()
    dead = leader.id
    c.router.isolate(dead)
    leader = c.tick_until_leader(max_ticks=150)
    assert leader.id != dead
    samples.append(c.clock.monotonic() - t0)
    c.router.heal(dead)
    c.tick_all(5)                    # deposed leader observes the new term

    # ---- leg B: ENOSPC degrade + probe lift ---------------------------
    leader = c.tick_until_leader()
    t0 = c.clock.monotonic()
    res = {}
    failpoints.arm("raft.wal.fsync", error=failpoints.enospc)
    try:
        leader.propose({"op": "enospc"}, f"enospc-{seed}",
                       lambda ok, err: res.update(ok=ok, err=err))
        c.settle()
        assert res.get("ok") is False
        assert leader.storage_degraded, "ENOSPC must degrade the leader"
    finally:
        failpoints.disarm_all()
    recovered = False
    for _ in range(200):
        c.tick_all()
        if leader.storage_degraded or c.leader() is None:
            continue
        if c.propose({"op": "post-enospc", "s": seed}):
            recovered = True
            break
    assert recovered, "cluster never committed after the ENOSPC lifted"
    samples.append(c.clock.monotonic() - t0)

    # ---- leg C: lagging member catch-up under chunk loss --------------
    leader = c.tick_until_leader()
    lag = rng.choice([i for i in c.nodes if i != leader.id])
    c.router.isolate(lag)
    # live store churn on the leader: the snapshot the lagging member
    # installs must carry state it never saw through the log
    def churn_tx(tx):
        for k in range(4):
            t = Task(id=f"s{seed}-churn-{k}", service_id=f"s{seed}-svc",
                     slot=100 + k)
            t.status.state = TaskState.PENDING
            t.desired_state = TaskState.RUNNING
            tx.create(t)

    stores[leader.id].update(churn_tx)
    for k in range(churn):
        assert c.propose({"op": "churn", "k": k})
    assert leader.snapshot_index > 0, "storm needs a compacted log"

    lag_node = c.nodes[lag]
    installs0 = lag_node.snap_installs
    adopted0 = stores[lag].op_counts.get("restore_columnar_adopted", 0)
    drops = rng.randint(1, 3)
    t0 = c.clock.monotonic()
    c.router.heal(lag)
    failpoints.arm("raft.snap.chunk_drop", value=True, times=drops)
    try:
        caught = False
        for _ in range(600):
            c.tick_all()
            if lag_node.snapshot_index == leader.snapshot_index \
                    and lag_node.last_applied >= leader.commit_index:
                caught = True
                break
    finally:
        failpoints.disarm_all()
    assert caught, "lagging member never caught up"
    samples.append(c.clock.monotonic() - t0)

    # judged invariants: the stream resumed (never silently re-bootstrapped),
    # the member installed, and its restore ADOPTED the columnar section
    assert lag_node.snap_installs >= installs0 + 1
    assert leader.snap_resume_suffix >= 1, \
        "dropped chunks must recover via a suffix resume"
    assert leader.snap_chunks_resent >= 1
    assert stores[lag].op_counts.get("restore_columnar_adopted", 0) \
        >= adopted0 + 1, stores[lag].op_counts
    # columnar fast restore is bit-equal to a from-scratch rebuild on
    # EVERY store after the storm (the 50-wave pin's restore extension)
    for i, st in stores.items():
        assert _columnar_matches_rebuild(st), f"store {i} columnar drift"
    # the installed member converged onto the leader's store image
    assert ColumnarTasks.snapshots_equal(
        stores[lag].columnar.snapshot(),
        stores[leader.id].columnar.snapshot())

    specs = slo_mod.parse_slo_arg(slo_arg)
    report = slo_mod.evaluate_samples(specs, samples)
    assert report.ok, report.render()
    out = report.as_dict()
    out["legs"] = {"leader_kill_s": round(samples[0], 3),
                   "enospc_lift_s": round(samples[1], 3),
                   "snapshot_catchup_s": round(samples[2], 3)}
    return out


@pytest.mark.parametrize("seed", FAST_SEEDS)
def test_recovery_storm_fast(seed, tmp_path):
    with chaos_seed(seed):
        rep = run_recovery_storm(seed, tmp_path)
        assert len(rep["legs"]) == 3


@pytest.mark.slow
@pytest.mark.chaos
@pytest.mark.parametrize("seed", SOAK_SEEDS)
def test_recovery_storm_soak(seed, tmp_path):
    with chaos_seed(seed):
        run_recovery_storm(seed, tmp_path, churn=40,
                           slo_arg="p50:45.0,p99:120.0")


def test_storm_replay_is_deterministic(tmp_path):
    """Same seed ⇒ same fake-clock leg durations (the CHAOS_SEED replay
    contract: every sample derives from the seed and the shared
    FakeClock, never wall time)."""
    a = run_recovery_storm(101, tmp_path / "a")
    b = run_recovery_storm(101, tmp_path / "b")
    assert a["legs"] == b["legs"]


# ------------------------------------------- suffix-resume protocol pins
def _drive_snapshot_stream(c, leader, follower_id, drop_seqs=(),
                           churn=30):
    """Isolate `follower_id`, compact the leader past it, heal, and let
    the stream run with the given first-attempt seqs dropped at the
    router. Returns the list of chunk messages that REACHED the
    follower."""
    c.router.isolate(follower_id)
    for k in range(churn):
        assert c.propose({"op": "fill", "k": k})
    assert leader.snapshot_index > 0
    delivered = []
    dropped = {s: False for s in drop_seqs}
    direct = c.router.send

    def send(frm, msg):
        if getattr(msg, "kind", "") == "snap_chunk" \
                and msg.to == follower_id:
            if msg.seq in dropped and not dropped[msg.seq]:
                dropped[msg.seq] = True
                return
            delivered.append(msg)
        direct(frm, msg)

    c.router.send = send
    c.router.heal(follower_id)
    c.tick_all(2)                    # heartbeat discovers the gap; stream
    return delivered


def test_suffix_resend_op_guard(tmp_path):
    """Acceptance: a lost chunk provably re-sends ONLY the missing
    suffix — exact chunk-count guard, never the whole blob."""
    c, _stores, _st = _mk_cluster(tmp_path, "guard", snapshot_interval=20)
    leader = c.elect(1)
    _drive_snapshot_stream(c, leader, follower_id=3, drop_seqs=(2,))
    total = leader.snap_chunks_sent
    assert total >= _BLOB_CHUNKS, "stream must span multiple chunks"
    assert c.nodes[3].snapshot_index == 0, "incomplete stream installed"
    assert leader.snap_resume_suffix == 0

    # TTL expires → ONLY chunks past the acked contiguous prefix (0..1)
    # go out again: total - 2 of them, strictly fewer than the blob
    c.tick_all(SNAPSHOT_RESEND_TICKS + 5)
    assert leader.snap_resume_suffix == 1
    assert leader.snap_chunks_resent == total - 2
    assert leader.snap_chunks_resent < total
    assert c.nodes[3].snapshot_index == leader.snapshot_index
    assert 3 not in leader._snap_pending


def test_resend_ttl_is_fakeclock_driven(tmp_path):
    """Satellite 2: the pause TTL is a CLOCK deadline (the harness
    FakeClock), not a wall-time sleep — no resend a tick before it
    expires, resend right after."""
    c, _stores, _st = _mk_cluster(tmp_path, "ttl", snapshot_interval=20)
    leader = c.elect(1)
    _drive_snapshot_stream(c, leader, follower_id=3, drop_seqs=(1,))
    assert leader.snap_resume_suffix == 0

    c.tick_all(SNAPSHOT_RESEND_TICKS - 5)     # just short of the deadline
    assert leader.snap_resume_suffix == 0, "resent before the TTL expired"
    assert c.nodes[3].snapshot_index == 0
    c.tick_all(10)                            # past it
    assert leader.snap_resume_suffix == 1
    assert c.nodes[3].snapshot_index == leader.snapshot_index


def test_ack_progress_rearms_resend_deadline(tmp_path):
    """A slow but PROGRESSING stream is never re-blasted: every ack that
    advances the contiguous watermark pushes the resend deadline out."""
    c, _stores, _st = _mk_cluster(tmp_path, "rearm", snapshot_interval=20)
    leader = c.elect(1)
    c.router.isolate(3)
    for k in range(30):
        assert c.propose({"op": "fill", "k": k})
    assert leader.snapshot_index > 0

    held = []
    direct = c.router.send

    def send(frm, msg):
        if getattr(msg, "kind", "") == "snap_chunk" and msg.to == 3:
            held.append((frm, msg))
            return
        direct(frm, msg)

    c.router.send = send
    c.router.heal(3)
    c.tick_all(2)
    assert len(held) >= _BLOB_CHUNKS
    # trickle one chunk per ~60% of a TTL: each delivery acks progress,
    # so the cumulative transfer far exceeds one TTL without any resend
    for frm, msg in list(held):
        c.tick_all(int(SNAPSHOT_RESEND_TICKS * 0.6))
        direct(frm, msg)
        c.settle()
    assert c.nodes[3].snapshot_index == leader.snapshot_index
    assert leader.snap_resume_suffix == 0, \
        "progressing stream was re-blasted"


def test_reassembly_buffer_caps_and_eviction(tmp_path):
    """Satellite 1: the follower's reassembly plane is bounded — streams
    whose declared size exceeds the cap (or with malformed framing) are
    rejected and counted, and at most ONE live buffer per sender
    survives (a newer stream evicts the abandoned one eagerly)."""
    c, _stores, _st = _mk_cluster(tmp_path, "cap", snapshot_interval=1000)
    leader = c.elect(1)
    f = c.nodes[3]
    base = dict(frm=leader.id, to=3, term=f.term, snapshot_term=1,
                members={}, removed=[])

    over = f.snap_stream_max_bytes // SNAPSHOT_CHUNK_BYTES + 1
    rejected0 = f.snap_chunks_rejected
    for bad in (
        SnapshotChunk(**base, snapshot_index=50, seq=0, total=over,
                      chunk=b"x"),                      # declared too big
        SnapshotChunk(**base, snapshot_index=50, seq=3, total=2,
                      chunk=b"x"),                      # seq out of range
        SnapshotChunk(**base, snapshot_index=50, seq=0, total=0,
                      chunk=b"x"),                      # no framing
        SnapshotChunk(**base, snapshot_index=50, seq=0, total=2,
                      chunk=b"x" * (SNAPSHOT_CHUNK_BYTES + 1)),  # fat chunk
    ):
        f.step(bad)
    f.process_all()
    assert f.snap_chunks_rejected == rejected0 + 4
    assert not f._snap_chunks, "rejected stream left a buffer behind"

    # eager eviction: an abandoned stream's buffer dies the moment the
    # sender opens a newer one; a late chunk of the old stream is ignored
    f.step(SnapshotChunk(**base, snapshot_index=50, seq=0, total=3,
                         chunk=b"a"))
    f.step(SnapshotChunk(**base, snapshot_index=60, seq=0, total=3,
                         chunk=b"b"))
    f.step(SnapshotChunk(**base, snapshot_index=50, seq=1, total=3,
                         chunk=b"a"))
    f.process_all()
    assert set(f._snap_chunks) == {(leader.id, 60)}
    assert set(f._snap_contig) == {(leader.id, 60)}


def test_install_crash_window_leaves_no_divergent_tail(tmp_path):
    """Satellite 3: a crash INSIDE the install window (after the WAL
    truncate, before the new snapshot lands) must leave old-snapshot +
    a consistent prefix — a restart may be behind, but never splices a
    stale tail after the new snapshot."""
    c, _stores, storages = _mk_cluster(tmp_path, "crash",
                                       snapshot_interval=12)
    leader = c.elect(1)
    for k in range(5):
        assert c.propose({"op": "pre", "k": k})
    c.router.isolate(3)
    for k in range(20):
        assert c.propose({"op": "fill", "k": k})
    new_snap = leader.snapshot_index
    assert new_snap > 0
    pre_snap = c.nodes[3].snapshot_index          # 0: never installed one
    pre_last = c.nodes[3]._last_index()
    assert pre_last < new_snap, "member must need the snapshot"

    c.router.heal(3)
    failpoints.arm("raft.snap.install", error=OSError("crash mid-install"))
    try:
        with pytest.raises(OSError, match="crash mid-install"):
            c.tick_all(3)
    finally:
        failpoints.disarm_all()

    # "restart": reload the member's storage fresh, as a new process would
    loaded = RaftStorage(str(tmp_path / "crash-r3")).load()
    assert loaded.snapshot_index == pre_snap, \
        "crash window persisted the NEW snapshot (truncate-before-save broken)"
    indexes = [e.index for e in loaded.entries]
    assert all(i <= new_snap for i in indexes), \
        f"divergent tail past the snapshot survived: {indexes}"
    assert indexes == sorted(set(indexes)), f"non-contiguous tail: {indexes}"
    # and the survivor state is bootable: a fresh node recovers from it
    from swarmkit_tpu.raft.node import RaftNode

    reborn = RaftNode(raft_id=3, transport=None,
                      storage=RaftStorage(str(tmp_path / "crash-r3")))
    assert reborn.snapshot_index == pre_snap
    assert reborn._last_index() <= new_snap


# --------------------------------------------- chunk loss/dup/reorder fuzz
@pytest.mark.parametrize("seed", range(20))
def test_chunk_stream_fuzz_installs_byte_identical(seed, tmp_path):
    """Satellite 3 fuzz: under seeded chunk loss, duplication, and
    reordering the follower still installs, and the restored state is
    byte-identical to a clean transfer of the same blob."""
    with chaos_seed(seed):
        rng = random.Random(seed)
        c, _stores, _st = _mk_cluster(tmp_path, f"fz{seed}",
                                      snapshot_interval=15, seed=seed,
                                      pad_chunks=3)
        leader = c.elect(1)
        restored = {}
        c.nodes[3].restore_state = \
            lambda d: restored.update(blob=codec.dumps(d))
        c.router.isolate(3)
        for k in range(22):
            assert c.propose({"op": "fz", "k": k})
        assert leader.snapshot_index > 0

        held = []
        direct = c.router.send

        def send(frm, msg):
            if getattr(msg, "kind", "") == "snap_chunk" and msg.to == 3:
                r = rng.random()
                if r < 0.25:
                    return                        # lost
                held.append((frm, msg))
                if r < 0.45:
                    held.append((frm, msg))       # duplicated
                return
            direct(frm, msg)

        c.router.send = send
        c.router.heal(3)
        installed = False
        for _ in range(25 * SNAPSHOT_RESEND_TICKS):
            rng.shuffle(held)                     # reordered delivery
            while held:
                frm, msg = held.pop()
                direct(frm, msg)
            c.settle()
            if c.nodes[3].snapshot_index == leader.snapshot_index:
                installed = True
                break
            c.tick_all()
        assert installed, "mangled stream never installed"
        # byte-identity with a clean transfer: the leader's cached blob
        # IS what a loss-free stream delivers, chunking is content-blind
        assert leader._snap_blob[0] == leader.snapshot_index
        clean = codec.dumps(codec.loads(leader._snap_blob[1]))
        assert restored["blob"] == clean
        assert c.nodes[3].last_applied >= leader.snapshot_index
