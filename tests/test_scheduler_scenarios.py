"""Event-driven scheduler walkthroughs, modeled on the reference's
scenario tests (manager/scheduler/scheduler_test.go): the plugin-filter
scenario (:3100-3186), availability changes mid-stream (drain/pause),
spread-preference rebalancing on node join, and host-port churn.

These complement the parity/property suites: parity proves the two fill
engines agree; scenarios prove the LIVE event loop converges through
cluster churn the way the reference's walkthroughs do."""
import pytest

from swarmkit_tpu.api.specs import (
    EndpointSpec,
    Placement,
    PlacementPreference,
    PortConfig,
    VolumeMount,
)
from swarmkit_tpu.api.types import NodeAvailability, TaskState
from swarmkit_tpu.scheduler.scheduler import Scheduler
from swarmkit_tpu.store import by
from swarmkit_tpu.store.memory import MemoryStore

from test_scheduler import pending_task, ready_node, wait_for


@pytest.fixture
def store():
    return MemoryStore()


@pytest.fixture
def sched(store):
    s = Scheduler(store)
    s.start()
    yield s
    s.stop()


def assigned(store, pred=lambda t: True):
    return [t for t in store.view().find_tasks(
        by.ByTaskState(TaskState.ASSIGNED)) if pred(t)]


def node_of(store, task_id):
    t = store.view().get_task(task_id)
    return t.node_id if t else None


# ----------------------------------------------- plugin filter scenario


def plugin_task(tid, slot, driver="nfs"):
    from swarmkit_tpu.api.specs import ContainerSpec

    t = pending_task(tid, slot=slot)
    t.spec.runtime = ContainerSpec(
        command=["true"],
        mounts=[VolumeMount(source=f"{driver}/data", target="/data")])
    return t


def test_plugin_filter_scenario(store, sched):
    """scheduler_test.go:3100-3186: tasks needing a volume driver land
    only on nodes advertising the plugin; a node that GAINS the plugin
    becomes eligible and unblocks pending work."""
    def setup(tx):
        for i in range(6):
            n = ready_node(f"node-{i}")
            if i % 3 == 0:   # 1 in 3 nodes carries the plugin
                n.description.plugins = [("Volume", "nfs")]
            tx.create(n)
        for i in range(4):
            tx.create(plugin_task(f"pt-{i}", slot=i + 1))

    store.update(setup)
    assert wait_for(lambda: len(assigned(store)) == 4, timeout=10)
    for t in assigned(store):
        assert t.node_id in ("node-0", "node-3"), t.node_id

    # a task needing a driver NO node has stays pending, with the filter
    # explanation written to its status
    store.update(lambda tx: tx.create(plugin_task("pt-gluster", 10,
                                                  driver="gluster")))

    def explained():
        t = store.view().get_task("pt-gluster")
        return t.status.state == TaskState.PENDING and t.status.message
    assert wait_for(explained, timeout=10)

    # the plugin arrives on a node (engine upgrade): the task unblocks
    def upgrade(tx):
        n = tx.get_node("node-1").copy()
        n.description.plugins = [("Volume", "gluster")]
        tx.update(n)
    store.update(upgrade)
    assert wait_for(lambda: node_of(store, "pt-gluster") == "node-1",
                    timeout=10)


# --------------------------------------------- drain / pause mid-stream


def test_drain_and_pause_mid_stream(store, sched):
    """Availability flips between waves: DRAIN and PAUSE nodes stop
    receiving new tasks; reactivation restores them (scheduler_test.go
    node-availability walkthroughs)."""
    def setup(tx):
        for i in range(3):
            tx.create(ready_node(f"n{i}"))
        for i in range(6):
            tx.create(pending_task(f"w1-{i}", slot=i + 1))

    store.update(setup)
    assert wait_for(lambda: len(assigned(store)) == 6, timeout=10)
    assert {t.node_id for t in assigned(store)} == {"n0", "n1", "n2"}

    def flip(tx, node_id, avail):
        n = tx.get_node(node_id).copy()
        n.spec.availability = avail
        tx.update(n)

    store.update(lambda tx: flip(tx, "n0", NodeAvailability.DRAIN))
    store.update(lambda tx: flip(tx, "n1", NodeAvailability.PAUSE))

    def wave2(tx):
        for i in range(4):
            tx.create(pending_task(f"w2-{i}", service_id="svc2",
                                   slot=i + 1))
    store.update(wave2)
    assert wait_for(
        lambda: len(assigned(store, lambda t: t.service_id == "svc2")) == 4,
        timeout=10)
    assert {t.node_id for t in
            assigned(store, lambda t: t.service_id == "svc2")} == {"n2"}

    # reactivate: the next wave uses every node again
    store.update(lambda tx: flip(tx, "n0", NodeAvailability.ACTIVE))
    store.update(lambda tx: flip(tx, "n1", NodeAvailability.ACTIVE))

    def wave3(tx):
        for i in range(6):
            tx.create(pending_task(f"w3-{i}", service_id="svc3",
                                   slot=i + 1))
    store.update(wave3)
    assert wait_for(
        lambda: len(assigned(store, lambda t: t.service_id == "svc3")) == 6,
        timeout=10)
    assert {t.node_id for t in
            assigned(store, lambda t: t.service_id == "svc3")} == \
        {"n0", "n1", "n2"}


# ------------------------------------- preference tree on node join


def spread_task(tid, slot, svc="spreader"):
    t = pending_task(tid, service_id=svc, slot=slot)
    t.spec.placement = Placement(preferences=[
        PlacementPreference(spread_descriptor="node.labels.zone")])
    return t


def test_preference_tree_rebalances_on_node_join(store, sched):
    """nodeset.go tree semantics: with one zone, everything lands there;
    when a second zone joins, NEW tasks flow to the emptier branch until
    the zones balance (scheduler_test.go preference walkthroughs)."""
    def setup(tx):
        for i in range(2):
            tx.create(ready_node(f"za-{i}", labels={"zone": "a"}))
        for i in range(6):
            tx.create(spread_task(f"s1-{i}", slot=i + 1))

    store.update(setup)
    assert wait_for(lambda: len(assigned(store)) == 6, timeout=10)
    assert all(t.node_id.startswith("za-") for t in assigned(store))

    # zone b joins, empty
    store.update(lambda tx: (tx.create(ready_node("zb-0",
                                                  labels={"zone": "b"})),
                             tx.create(ready_node("zb-1",
                                                  labels={"zone": "b"}))))

    def wave2(tx):
        for i in range(6):
            tx.create(spread_task(f"s2-{i}", slot=100 + i))
    store.update(wave2)
    assert wait_for(
        lambda: len(assigned(store, lambda t: t.id.startswith("s2-"))) == 6,
        timeout=10)
    by_zone = {"a": 0, "b": 0}
    for t in assigned(store):
        by_zone["a" if t.node_id.startswith("za-") else "b"] += 1
    # 12 tasks total must balance 6/6 across the two zones: the whole
    # second wave flowed to the previously-empty zone b
    assert by_zone == {"a": 6, "b": 6}, by_zone


# ------------------------------------------------- host-port churn


def port_task(tid, svc, port=8080):
    t = pending_task(tid, service_id=svc, slot=1)
    t.endpoint = EndpointSpec(ports=[PortConfig(
        protocol="tcp", target_port=80, published_port=port,
        publish_mode="host")])
    return t


def test_host_port_churn(store, sched):
    """Host-published ports are node-exclusive: a second service's task
    waits until the holder dies, then takes the freed port
    (scheduler_test.go host-port scenarios)."""
    store.update(lambda tx: (tx.create(ready_node("only")),
                             tx.create(port_task("holder", "svcA"))))
    assert wait_for(lambda: node_of(store, "holder") == "only", timeout=10)

    # same port, same node pool: must stay pending
    store.update(lambda tx: tx.create(port_task("waiter", "svcB")))

    def waiter_pending_with_reason():
        t = store.view().get_task("waiter")
        return (t.status.state == TaskState.PENDING
                and not t.node_id and t.status.message)
    assert wait_for(waiter_pending_with_reason, timeout=10)

    # the holder dies: its ports free, the waiter schedules
    def kill(tx):
        t = tx.get_task("holder").copy()
        t.status.state = TaskState.FAILED
        t.desired_state = TaskState.SHUTDOWN
        tx.update(t)
    store.update(kill)
    assert wait_for(lambda: node_of(store, "waiter") == "only", timeout=10)

    # a different port was never blocked
    store.update(lambda tx: tx.create(port_task("other", "svcC", port=9090)))
    assert wait_for(lambda: node_of(store, "other") == "only", timeout=10)
