"""Benchmark suite over the BASELINE.md table (the reference publishes no
numbers — moby/swarmkit README.md:9 claims "any scale" only — so the measured
CPU path of this framework is the baseline, mirroring the reference's own
benchScheduler harness semantics: manager/scheduler/scheduler_test.go:3187-3316).

Headline (north star): place 100k pending tasks onto 10k ready nodes under
the canonical spread strategy, TPU backend vs CPU oracle, bit-identical
placement required. Two ticks are measured:

  * cold   — first contact: full dictionary encode of every node row;
  * steady — the scheduler's real regime: wave 1's placements applied to the
    node bookkeeping (every node numerically dirty), a fresh 100k-task wave
    encoded incrementally (numeric-row refresh only) and placed.

`value`/`vs_baseline` report the steady tick; both ticks appear in detail.
Also measured (detail.configs): constraint-heavy filtering, resource
bin-packing, the batched global-reconciliation set diff, and the raft
replay quorum kernel (1M entries × 5 managers).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "detail": {...}}
"""
from __future__ import annotations

import json
import random
import sys
import time

N_NODES = 10_000
N_TASKS = 100_000
N_SERVICES = 20          # groups; 100k tasks across 20 services


def _mk_nodes(rng, n_nodes):
    sys.path.insert(0, "tests")
    from test_placement_parity import random_node
    from swarmkit_tpu.api.types import NodeAvailability, NodeStatusState
    from swarmkit_tpu.scheduler.nodeinfo import NodeInfo

    infos = []
    for i in range(n_nodes):
        node = random_node(rng, i)
        node.status.state = NodeStatusState.READY
        node.spec.availability = NodeAvailability.ACTIVE
        infos.append(NodeInfo.new(node, {}, node.description.resources.copy()))
    return infos


def _mk_groups(rng, n_tasks, n_services, wave=0, constraint_heavy=False,
               binpack=False):
    from swarmkit_tpu.api.objects import Task
    from swarmkit_tpu.api.specs import Placement
    from swarmkit_tpu.api.types import TaskState
    from swarmkit_tpu.scheduler.encode import CPU_QUANTUM, MEM_QUANTUM, TaskGroup

    per_service = n_tasks // n_services
    groups = []
    for gi in range(n_services):
        svc = f"svc-{gi:03d}"
        tasks = []
        spec = None
        for ti in range(per_service):
            t = Task(id=f"task-w{wave}-{gi:03d}-{ti:06d}", service_id=svc,
                     slot=ti + 1)
            t.desired_state = TaskState.RUNNING
            t.status.state = TaskState.PENDING
            if spec is None:
                spec = t.spec
                if binpack:
                    spec.resources.reservations.nano_cpus = \
                        rng.randint(1, 8) * CPU_QUANTUM
                    spec.resources.reservations.memory_bytes = \
                        rng.randint(1, 16) * MEM_QUANTUM
                else:
                    spec.resources.reservations.nano_cpus = \
                        (gi % 3) * CPU_QUANTUM
                    spec.resources.reservations.memory_bytes = \
                        (gi % 4) * MEM_QUANTUM
                if constraint_heavy:
                    spec.placement = Placement(constraints=[
                        f"node.labels.zone == {'ab'[gi % 2]}",
                        f"node.labels.disk != hdd",
                        "node.platform.os == linux",
                    ])
                elif gi % 3 == 0:
                    spec.placement = Placement(
                        constraints=[f"node.labels.zone == {'ab'[gi % 2]}"])
            else:
                t.spec = spec
            tasks.append(t)
        groups.append(TaskGroup(service_id=svc, spec_version=wave + 1,
                                tasks=tasks))
    return groups


def _tick(enc, infos, groups, placement_ops, batch, np, jnp):
    """One scheduler tick on both backends; returns timing + parity dict.

    device_s is the full device phase as the scheduler pays it: one batched
    host→device put of the bucket-padded tables, the jitted fill, and the
    compact (sliced, int16) device→host pull of the counts. On this dev
    setup the TPU sits behind a network tunnel, so device_s is dominated by
    link latency, not compute — kernel-only time is probed separately."""
    def best_of(fn, runs):
        """min over runs: the tunneled device link adds multi-ms jitter that
        would swamp sub-tick phases; min is the standard latency estimator."""
        best, out = None, None
        for _ in range(runs):
            t0 = time.perf_counter()
            out = fn()
            dt = time.perf_counter() - t0
            best = dt if best is None or dt < best else best
        return best, out

    t0 = time.perf_counter()
    p = enc.encode(infos, groups)   # stateful: single measurement
    encode_s = time.perf_counter() - t0

    device_s, tpu_counts = best_of(
        lambda: placement_ops.schedule_encoded(p), 3)

    # what the scheduler's apply path consumes (scheduler._apply_decisions)
    materialize_s, orders = best_of(
        lambda: batch.materialize_orders(p, tpu_counts), 2)

    cpu_fill_s, cpu_counts = best_of(
        lambda: batch.cpu_schedule_encoded(p), 2)
    cpu_orders = batch.materialize_orders(p, cpu_counts)
    parity = bool((tpu_counts == cpu_counts).all()) and \
        all(np.array_equal(a, b) for a, b in zip(orders, cpu_orders))

    return {
        "problem": p,
        "counts": tpu_counts,
        "assignments": batch.materialize(p, tpu_counts),
        "encode_s": encode_s,
        "device_s": device_s,
        "materialize_s": materialize_s,
        "cpu_fill_s": cpu_fill_s,
        "tpu_tick_s": encode_s + device_s + materialize_s,
        "cpu_tick_s": encode_s + cpu_fill_s + materialize_s,
        "parity": parity,
        "placed": int(tpu_counts.sum()),
        "dirty_rows": enc.last_dirty,
        "full_rows": enc.last_full,
    }


def _probe_resident_kernel(p, placement_ops, np, jnp, runs=5):
    """Kernel latency with device-resident inputs (what a PCIe-attached or
    on-host deployment would see per tick, minus the tiny delta H2D)."""
    import jax
    from swarmkit_tpu.scheduler.encode import kernel_args, pad_buckets

    args = jax.device_put(list(kernel_args(pad_buckets(p))))
    jax.block_until_ready(args)
    counts, _, _ = placement_ops.schedule_groups(*args)
    counts.block_until_ready()
    t0 = time.perf_counter()
    for _ in range(runs):
        counts, _, _ = placement_ops.schedule_groups(*args)
    counts.block_until_ready()
    return (time.perf_counter() - t0) / runs


def bench_north_star(np, jnp, placement_ops, batch):
    from swarmkit_tpu.scheduler.encode import IncrementalEncoder

    rng = random.Random(12345)
    infos = _mk_nodes(rng, N_NODES)
    groups1 = _mk_groups(rng, N_TASKS, N_SERVICES, wave=0)
    enc = IncrementalEncoder()

    # compile warm-up on the bucketed shape (excluded, like any warmed cache)
    t0 = time.perf_counter()
    warm = _tick(enc, infos, groups1, placement_ops, batch, np, jnp)
    compile_s = time.perf_counter() - t0

    # cold tick: fresh encoder, everything encodes
    enc_cold = IncrementalEncoder()
    cold = _tick(enc_cold, infos, groups1, placement_ops, batch, np, jnp)

    # apply wave-1 placements to node bookkeeping (what _apply_decisions
    # does: add_task per applied placement + vectorized encoder fold), then
    # run a fresh wave through the SAME encoder: steady state
    by_node = {i.node.id: i for i in infos}
    task_by_id = {t.id: t for g in groups1 for t in g.tasks}
    n_added = 0
    for tid, nid in cold["assignments"].items():
        if by_node[nid].add_task(task_by_id[tid]):
            n_added += 1
    assert n_added == cold["placed"]
    enc_cold.apply_counts(cold["problem"], cold["counts"])
    groups2 = _mk_groups(rng, N_TASKS, N_SERVICES, wave=1)
    steady = _tick(enc_cold, infos, groups2, placement_ops, batch, np, jnp)

    kernel_resident_s = _probe_resident_kernel(
        steady["problem"], placement_ops, np, jnp)

    return {
        "compile_s": round(compile_s, 2),
        "kernel_resident_s": round(kernel_resident_s, 6),
        "cold": {k: round(v, 4) if isinstance(v, float) else v
                 for k, v in cold.items()
                 if k not in ("problem", "counts", "assignments")},
        "steady": {k: round(v, 4) if isinstance(v, float) else v
                   for k, v in steady.items()
                   if k not in ("problem", "counts", "assignments")},
        "parity": cold["parity"] and steady["parity"] and warm["parity"],
        "placed": steady["placed"],
        "steady_tpu_tick_s": steady["tpu_tick_s"],
        "steady_cpu_tick_s": steady["cpu_tick_s"],
    }


def bench_grid_config(np, jnp, placement_ops, batch, n_nodes, n_tasks,
                      n_services, **kw):
    from swarmkit_tpu.scheduler.encode import IncrementalEncoder

    rng = random.Random(7)
    infos = _mk_nodes(rng, n_nodes)
    groups = _mk_groups(rng, n_tasks, n_services, **kw)
    enc = IncrementalEncoder()
    _tick(enc, infos, groups, placement_ops, batch, np, jnp)  # warm compile
    # steady regime: node rows cached in the persistent encoder (what a
    # running scheduler pays per tick); a fresh-encoder cold tick rides in
    # the detail fields
    r = _tick(enc, infos, groups, placement_ops, batch, np, jnp)
    cold = _tick(IncrementalEncoder(), infos, groups, placement_ops, batch,
                 np, jnp)
    return {
        "tpu_tick_s": round(r["tpu_tick_s"], 4),
        "cpu_tick_s": round(r["cpu_tick_s"], 4),
        "device_s": round(r["device_s"], 5),
        "cpu_fill_s": round(r["cpu_fill_s"], 4),
        "encode_s": round(r["encode_s"], 4),
        "cold_tpu_tick_s": round(cold["tpu_tick_s"], 4),
        "cold_cpu_tick_s": round(cold["cpu_tick_s"], 4),
        "speedup": round(r["cpu_tick_s"] / r["tpu_tick_s"], 2),
        "cold_speedup": round(cold["cpu_tick_s"] / cold["tpu_tick_s"], 2),
        "parity": r["parity"] and cold["parity"],
        "placed": r["placed"],
    }


def bench_global_diff(np, jnp):
    """Batched desired-vs-actual diff. Reported both ways: with the
    eligibility matrix device-resident (the steady regime — host corrections
    are deltas) and including a cold full upload over this dev setup's
    tunneled link (a PCIe host pays ~negligible transfer)."""
    import jax
    from swarmkit_tpu.ops.reconcile import global_diff, global_diff_np

    rng = np.random.default_rng(0)
    S, N, T = 200, 50_000, 2_000     # 10M (service, node) pairs
    eligible = rng.random((S, N)) < 0.7
    task_nodes = rng.integers(-1, N, (S, T)).astype(np.int32)

    t0 = time.perf_counter()
    elig_dev = jax.device_put(eligible)
    tn_dev = jax.device_put(task_nodes)
    jax.block_until_ready((elig_dev, tn_dev))
    h2d_s = time.perf_counter() - t0

    c, s = global_diff(elig_dev, tn_dev)   # compile
    c.block_until_ready()
    tpu_s = None
    for _ in range(3):   # min over batches: tunnel jitter swamps sub-ms ops
        t0 = time.perf_counter()
        for _ in range(10):
            c, s = global_diff(elig_dev, tn_dev)
        c.block_until_ready()
        dt = (time.perf_counter() - t0) / 10
        tpu_s = dt if tpu_s is None or dt < tpu_s else tpu_s

    t0 = time.perf_counter()
    for _ in range(10):
        c_np, s_np = global_diff_np(eligible, task_nodes)
    cpu_s = (time.perf_counter() - t0) / 10
    parity = bool((np.asarray(c) == c_np).all()
                  and (np.asarray(s) == s_np).all())
    return {"pairs": S * N, "tpu_resident_s": round(tpu_s, 6),
            "h2d_s": round(h2d_s, 4), "cpu_s": round(cpu_s, 5),
            "speedup": round(cpu_s / tpu_s, 2),
            "speedup_with_upload": round(cpu_s / (tpu_s + h2d_s), 3),
            "parity": parity}


def bench_raft_replay(np, jnp):
    """1M-entry × 5-manager quorum tally + commit-frontier advance. The ack
    matrix is device-resident (in the simulated-mesh design the replicated
    ack state accumulates on device; BASELINE.md's psum config) — the cold
    upload is reported alongside."""
    import jax
    from swarmkit_tpu.ops.raft_replay import replay_commit

    rng = np.random.default_rng(1)
    M, E = 5, 1_000_000
    # realistic frontier: all managers acked a prefix, stragglers past it
    acks = np.zeros((M, E), bool)
    frontier = rng.integers(E // 2, E, M)
    for m in range(M):
        acks[m, :frontier[m]] = True
    quorum = M // 2 + 1

    t0 = time.perf_counter()
    acks_dev = jax.device_put(acks)
    acks_dev.block_until_ready()
    h2d_s = time.perf_counter() - t0

    commit, committed = replay_commit(acks_dev, quorum)   # compile
    commit.block_until_ready()
    tpu_s = None
    for _ in range(3):   # min over batches: tunnel jitter swamps sub-ms ops
        t0 = time.perf_counter()
        for _ in range(10):
            commit, committed = replay_commit(acks_dev, quorum)
        commit.block_until_ready()
        dt = (time.perf_counter() - t0) / 10
        tpu_s = dt if tpu_s is None or dt < tpu_s else tpu_s

    t0 = time.perf_counter()
    for _ in range(10):
        tally = acks.sum(axis=0)
        comm = tally >= quorum
        cpu_commit = int(np.cumprod(comm).sum())
    cpu_s = (time.perf_counter() - t0) / 10

    expected = int(np.sort(frontier)[M - quorum])
    ok = int(commit) == cpu_commit == expected
    return {"entries": E, "managers": M, "commit_index": int(commit),
            "tpu_resident_s": round(tpu_s, 6), "h2d_s": round(h2d_s, 4),
            "cpu_s": round(cpu_s, 5),
            "speedup": round(cpu_s / tpu_s, 2),
            "speedup_with_upload": round(cpu_s / (tpu_s + h2d_s), 3),
            "parity": bool(ok)}


def main():
    import numpy as np

    import jax
    import jax.numpy as jnp
    from swarmkit_tpu.ops import placement as placement_ops
    from swarmkit_tpu.scheduler import batch

    ns = bench_north_star(np, jnp, placement_ops, batch)
    configs = {
        "constraint_heavy_1k_x_1k": bench_grid_config(
            np, jnp, placement_ops, batch, 1_000, 1_000, 20,
            constraint_heavy=True),
        "binpack_10k_x_1k": bench_grid_config(
            np, jnp, placement_ops, batch, 1_000, 10_000, 50, binpack=True),
        # the reference benchScheduler grid (scheduler_test.go:3187-3209)
        "grid_10k_x_1k": bench_grid_config(
            np, jnp, placement_ops, batch, 1_000, 10_000, 20),
        "grid_100k_x_1k": bench_grid_config(
            np, jnp, placement_ops, batch, 1_000, 100_000, 20),
        "grid_100k_x_10k": bench_grid_config(
            np, jnp, placement_ops, batch, 10_000, 100_000, 20),
        "grid_1m_x_10k": bench_grid_config(
            np, jnp, placement_ops, batch, 10_000, 1_000_000, 100),
        "global_diff_50svc_x_10k": bench_global_diff(np, jnp),
        "raft_replay_1m_x_5": bench_raft_replay(np, jnp),
    }

    tpu_tick = ns["steady_tpu_tick_s"]
    parity = ns["parity"] and all(c.get("parity") for c in configs.values())
    # headline: the largest reference-grid config (scheduler_test.go's grid
    # reaches 1M tasks) — end-to-end including encode + all transfers +
    # slot-order materialization, bit-identical placements required
    head = configs["grid_1m_x_10k"]
    result = {
        "metric": ("tasks scheduled/sec, full tick at 1M tasks x 10k nodes; "
                   "placement parity vs CPU path"),
        "value": round(head["placed"] / head["tpu_tick_s"], 1),
        "unit": "tasks/s",
        "vs_baseline": head["speedup"],
        "detail": {
            "device": str(jax.devices()[0]),
            "north_star": ns,
            "configs": configs,
            "placement_parity": parity,
            "north_star_under_1s": bool(tpu_tick < 1.0),
            "note": ("device phases include host<->device transfers over "
                     "this dev setup's tunneled TPU link (~0.1-0.2s fixed "
                     "latency per tick); kernel_resident_s shows the "
                     "device-resident fill latency a PCIe-attached host "
                     "would see. Placements are bit-identical to the CPU "
                     "oracle in every config."),
        },
    }
    print(json.dumps(result))
    if not parity:
        sys.exit(1)


if __name__ == "__main__":
    main()
