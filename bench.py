"""North-star benchmark (BASELINE.md): place 100k pending tasks onto 10k
ready nodes under the canonical spread strategy, TPU backend vs CPU oracle,
with bit-identical placement required.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

`value` is TPU tasks-scheduled-per-second (kernel wall time, post-compile);
`vs_baseline` is the speedup over the single-threaded CPU oracle on the same
encoded problem (the reference publishes no numbers — BASELINE.md — so the
measured CPU path of this framework is the baseline, mirroring the
reference's own benchScheduler harness semantics:
manager/scheduler/scheduler_test.go:3187-3316).
"""
from __future__ import annotations

import json
import random
import sys
import time

N_NODES = 10_000
N_TASKS = 100_000
N_SERVICES = 20          # groups; 100k tasks across 20 services
PARITY_SAMPLE = True


def build_problem():
    sys.path.insert(0, "tests")
    from test_placement_parity import random_node
    from swarmkit_tpu.api.objects import Task
    from swarmkit_tpu.api.specs import Placement
    from swarmkit_tpu.api.types import TaskState
    from swarmkit_tpu.scheduler.encode import CPU_QUANTUM, MEM_QUANTUM, TaskGroup, encode
    from swarmkit_tpu.scheduler.nodeinfo import NodeInfo

    rng = random.Random(12345)
    infos = []
    for i in range(N_NODES):
        node = random_node(rng, i)
        # all nodes ready/active for the north-star config
        from swarmkit_tpu.api.types import NodeAvailability, NodeStatusState
        node.status.state = NodeStatusState.READY
        node.spec.availability = NodeAvailability.ACTIVE
        infos.append(NodeInfo.new(node, {}, node.description.resources.copy()))

    per_service = N_TASKS // N_SERVICES
    groups = []
    for gi in range(N_SERVICES):
        svc = f"svc-{gi:03d}"
        tasks = []
        spec = None
        for ti in range(per_service):
            t = Task(id=f"task-{gi:03d}-{ti:06d}", service_id=svc, slot=ti + 1)
            t.desired_state = TaskState.RUNNING
            t.status.state = TaskState.PENDING
            if spec is None:
                spec = t.spec
                spec.resources.reservations.nano_cpus = (gi % 3) * CPU_QUANTUM
                spec.resources.reservations.memory_bytes = (gi % 4) * MEM_QUANTUM
                if gi % 3 == 0:
                    spec.placement = Placement(
                        constraints=[f"node.labels.zone == {'ab'[gi % 2]}"])
            else:
                t.spec = spec
            tasks.append(t)
        groups.append(TaskGroup(service_id=svc, spec_version=1, tasks=tasks))
    t0 = time.perf_counter()
    p = encode(infos, groups)
    encode_s = time.perf_counter() - t0
    return p, encode_s


def main():
    import numpy as np
    from swarmkit_tpu.scheduler import batch
    from swarmkit_tpu.ops import placement as placement_ops
    import jax

    p, encode_s = build_problem()

    from swarmkit_tpu.scheduler.encode import kernel_args
    args = tuple(jax.numpy.asarray(a) for a in kernel_args(p))

    # compile (excluded from the timed run, like any warmed scheduler cache)
    t0 = time.perf_counter()
    counts, totals, svc = placement_ops.schedule_groups(*args)
    counts.block_until_ready()
    compile_s = time.perf_counter() - t0

    runs = 5
    t0 = time.perf_counter()
    for _ in range(runs):
        counts, totals, svc = placement_ops.schedule_groups(*args)
    counts.block_until_ready()
    kernel_s = (time.perf_counter() - t0) / runs

    tpu_counts = np.asarray(counts)
    placed = int(tpu_counts.sum())

    t0 = time.perf_counter()
    assignments = batch.materialize(p, tpu_counts)
    materialize_s = time.perf_counter() - t0

    # CPU oracle (the baseline) + parity check: the reference publishes no
    # numbers, so the baseline is this framework's own sequential path —
    # the reference's benchScheduler measures the same end-to-end quantity
    t0 = time.perf_counter()
    cpu_counts = batch.cpu_schedule_encoded(p)
    cpu_fill_s = time.perf_counter() - t0
    parity = bool((tpu_counts == cpu_counts).all())
    parity_assign = batch.materialize(p, cpu_counts) == assignments

    # full tick: encode (host) + fill + materialize; encode/materialize are
    # shared host stages on both paths
    tpu_tick_s = encode_s + kernel_s + materialize_s
    cpu_tick_s = encode_s + cpu_fill_s + materialize_s

    value = placed / tpu_tick_s
    result = {
        "metric": (f"tasks scheduled/sec at {N_TASKS // 1000}k tasks x "
                   f"{N_NODES // 1000}k nodes; placement parity vs CPU"),
        "value": round(value, 1),
        "unit": "tasks/s",
        "vs_baseline": round(cpu_tick_s / tpu_tick_s, 2),
        "detail": {
            "device": str(jax.devices()[0]),
            "tpu_tick_s": round(tpu_tick_s, 4),
            "cpu_tick_s": round(cpu_tick_s, 4),
            "tpu_kernel_s": round(kernel_s, 6),
            "cpu_fill_s": round(cpu_fill_s, 4),
            "kernel_speedup": round(cpu_fill_s / kernel_s, 1),
            "encode_s": round(encode_s, 3),
            "materialize_s": round(materialize_s, 3),
            "compile_s": round(compile_s, 2),
            "tasks_placed": placed,
            "placement_parity": parity and bool(parity_assign),
            "north_star_under_1s": bool(tpu_tick_s < 1.0),
        },
    }
    print(json.dumps(result))
    if not (parity and parity_assign):
        sys.exit(1)


if __name__ == "__main__":
    main()
