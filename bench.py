"""Benchmark suite over the BASELINE.md table (the reference publishes no
numbers — moby/swarmkit README.md:9 claims "any scale" only — so the measured
CPU path of this framework is the baseline, mirroring the reference's own
benchScheduler harness semantics: manager/scheduler/scheduler_test.go:3187-3316).

Headline (north star): place 100k pending tasks onto 10k ready nodes under
the canonical spread strategy, TPU backend vs CPU oracle, bit-identical
placement required. Two regimes are measured:

  * cold   — first contact: full dictionary encode of every node row AND a
    full upload of the node tables to the device;
  * steady — the scheduler's real regime (round-2: device-RESIDENT node
    state, ops/resident.py; round-3: PIPELINED ticks, ops/pipeline.py):
    wave k's placements are folded on device by the kernel itself and on
    host by the encoder; wave k+1 ships only dirty-row deltas up and the
    sliced int16 counts down — and that counts D2H rides the tunnel in
    the background while the host commits wave k, so the blocking
    residual per tick is near zero.

`value`/`vs_baseline` report the steady tick; both appear in detail.
Also measured (detail.configs): constraint-heavy filtering, resource
bin-packing, the batched global-reconciliation set diff, and the raft
replay quorum kernel (1M entries × 5 managers) — both now device-resident
with per-round delta uploads, plus their full-upload cold numbers.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "detail": {...}}
"""
from __future__ import annotations

import json
import os
import random
import sys
import time

N_NODES = 10_000
N_TASKS = 100_000
N_SERVICES = 20          # groups; 100k tasks across 20 services


def _mk_nodes(rng, n_nodes, plugin_every=None):
    """plugin_every=k: every k-th node advertises the Volume/benchfs plugin
    (the reference's plugin-constrained grid runs with 1-in-3 eligible,
    scheduler_test.go:3210-3226)."""
    sys.path.insert(0, "tests")
    from test_placement_parity import random_node
    from swarmkit_tpu.api.types import NodeAvailability, NodeStatusState
    from swarmkit_tpu.scheduler.nodeinfo import NodeInfo

    infos = []
    for i in range(n_nodes):
        node = random_node(rng, i)
        node.status.state = NodeStatusState.READY
        node.spec.availability = NodeAvailability.ACTIVE
        if plugin_every is not None and i % plugin_every == 0:
            node.description.plugins = list(node.description.plugins) + [
                ("Volume", "benchfs")]
        infos.append(NodeInfo.new(node, {}, node.description.resources.copy()))
    return infos


def _mk_groups(rng, n_tasks, n_services, wave=0, constraint_heavy=False,
               binpack=False, plugin_volume=False):
    from swarmkit_tpu.api.objects import Task
    from swarmkit_tpu.api.specs import Placement, VolumeMount
    from swarmkit_tpu.api.types import TaskState
    from swarmkit_tpu.scheduler.encode import CPU_QUANTUM, MEM_QUANTUM, TaskGroup

    per_service = n_tasks // n_services
    groups = []
    for gi in range(n_services):
        svc = f"svc-{gi:03d}"
        tasks = []
        spec = None
        for ti in range(per_service):
            t = Task(id=f"task-w{wave}-{gi:03d}-{ti:06d}", service_id=svc,
                     slot=ti + 1)
            t.desired_state = TaskState.RUNNING
            t.status.state = TaskState.PENDING
            if spec is None:
                spec = t.spec
                if binpack:
                    spec.resources.reservations.nano_cpus = \
                        rng.randint(1, 8) * CPU_QUANTUM
                    spec.resources.reservations.memory_bytes = \
                        rng.randint(1, 16) * MEM_QUANTUM
                else:
                    spec.resources.reservations.nano_cpus = \
                        (gi % 3) * CPU_QUANTUM
                    spec.resources.reservations.memory_bytes = \
                        (gi % 4) * MEM_QUANTUM
                if constraint_heavy:
                    spec.placement = Placement(constraints=[
                        f"node.labels.zone == {'ab'[gi % 2]}",
                        f"node.labels.disk != hdd",
                        "node.platform.os == linux",
                    ])
                elif plugin_volume:
                    # "driver/source" mount convention → Volume/benchfs
                    # required on the node (PluginFilter.set_task)
                    from swarmkit_tpu.api.specs import ContainerSpec
                    spec.runtime = ContainerSpec(mounts=[
                        VolumeMount(source="benchfs/data", target="/data")])
                elif gi % 3 == 0:
                    spec.placement = Placement(
                        constraints=[f"node.labels.zone == {'ab'[gi % 2]}"])
            else:
                t.spec = spec
            tasks.append(t)
        # production tasks reach the commit OUT OF the scheduler's
        # unassigned pool (a dict keyed by task id), so every id string
        # arrives with its hash cached; mirror that data shape — without
        # it the bench's commit pays a cold str-hash per insert that the
        # production path never does
        _pool = {t.id: t for t in tasks}  # noqa: F841
        groups.append(TaskGroup(service_id=svc, spec_version=wave + 1,
                                tasks=tasks,
                                ids=[t.id for t in tasks]))
    return groups


def best_of(fn, runs):
    """min over runs: the tunneled device link adds multi-ms jitter that
    would swamp sub-tick phases; min is the standard latency estimator."""
    best, out = None, None
    for _ in range(runs):
        t0 = time.perf_counter()
        out = fn()
        dt = time.perf_counter() - t0
        best = dt if best is None or dt < best else best
    return best, out


def _tick(enc, rp, infos, groups, batch, np):
    """One scheduler tick: encode, device-resident fill, CPU oracle fill,
    parity check. device_s is everything the scheduler pays on the device
    side: delta/group-table upload, the jitted fill, and the sliced int16
    counts pull. The resident fill mutates device state, so it runs ONCE
    (no best-of) — exactly like a real tick."""
    t0 = time.perf_counter()
    p = enc.encode(infos, groups)
    encode_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    tpu_counts = rp.schedule(p)
    device_s = time.perf_counter() - t0

    materialize_s, orders = best_of(
        lambda: batch.materialize_orders(p, tpu_counts), 2)

    cpu_fill_s, cpu_counts = best_of(
        lambda: batch.cpu_schedule_encoded(p), 2)
    cpu_orders = batch.materialize_orders(p, cpu_counts)
    parity = bool((tpu_counts == cpu_counts).all()) and \
        all(np.array_equal(a, b) for a, b in zip(orders, cpu_orders))

    return {
        "problem": p,
        "counts": tpu_counts,
        "encode_s": encode_s,
        "device_s": device_s,
        "materialize_s": materialize_s,
        "cpu_fill_s": cpu_fill_s,
        "tpu_tick_s": encode_s + device_s + materialize_s,
        "cpu_tick_s": encode_s + cpu_fill_s + materialize_s,
        "parity": parity,
        "placed": int(tpu_counts.sum()),
        "dirty_rows": enc.last_dirty,
        "delta_rows_shipped": rp.uploads_delta_rows,
        "full_uploads": rp.uploads_full,
    }


def _apply_wave(enc, rp, infos, p, counts, batch):
    """What the scheduler's apply path does after a tick: wave-bulk
    NodeInfo bookkeeping, encoder fold, device correction bookkeeping."""
    by_node = {i.node.id: i for i in infos}
    infos_arr = [by_node[nid] for nid in p.node_ids]
    orders = batch.materialize_orders(p, counts)
    n_added = batch.apply_wave(infos_arr, p.groups, orders)
    assert n_added == int(counts.sum())
    assert enc.apply_counts(p, counts)
    rp.after_apply(p, counts)


def _probe_resident_kernel(p, placement_ops, runs=5):
    """Kernel latency with device-resident inputs (what a PCIe-attached or
    on-host deployment would see per tick, minus the tiny delta H2D).

    block_until_ready LIES through the tunnel (CLAUDE.md) — only a value
    pull is a true sync — so the probe times K chained dispatches closed
    by one scalar pull and subtracts the same measurement at K=0 (the
    pull's own round trip)."""
    import numpy as np_

    import jax
    from swarmkit_tpu.scheduler.encode import kernel_args, pad_buckets

    args = jax.device_put(list(kernel_args(pad_buckets(p))))
    counts, _, _ = placement_ops.schedule_groups(*args)   # compile
    int(np_.asarray(counts[0, 0]))

    def timed(k):
        best = None
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(k):
                c, _, _ = placement_ops.schedule_groups(*args)
            sync = counts if k == 0 else c
            int(np_.asarray(sync[0, 0]))          # true sync
            dt = time.perf_counter() - t0
            best = dt if best is None or dt < best else best
        return best

    return max(0.0, (timed(runs) - timed(0)) / runs)


def bench_scheduler_config(np, placement_ops, batch, n_nodes, n_tasks,
                           n_services, waves=8, plugin_every=None,
                           depth=3, async_commit=True, **kw):
    """Cold tick (fresh encoder + full device upload), then `waves` steady
    ticks through the TickPipeline (ops/pipeline.py) at pipeline depth
    `depth`: wave k's counts D2H rides the tunnel in the background
    under the commits of the k-1..k-depth waves — the legal schedule the
    production scheduler's debounce window provides naturally between
    ticks, made explicit for back-to-back bench waves. (Round 3's wave-
    bulk + native commit shrank the commit below the tunnel's fixed RTT,
    so one period no longer covers the transfer — depth > 1 restores the
    cover without adding fake work.) Groups are PRE-generated so only
    real scheduler work (never bench scaffolding) hides the transfer.

    Steady metrics:
      * tpu_tick_s — the classic decomposition (encode + device-blocking
        + materialize), where device-blocking is now dispatch + the pull
        RESIDUAL after overlap;
      * e2e_wave_s — a full pipelined period wall-clock, including the
        add_task commit loop, vs cpu_e2e_wave_s doing identical work with
        the CPU fill (both paths commit the same placements — parity).

    async_commit=True (round 6, the default; `--sync-commit` reverts)
    rides the heavy commit half on the background CommitWorker
    (ops/commit.py): a steady tick's wall is then pull-residual +
    commit BARRIER + fold + encode + dispatch — the barrier charges
    whatever commit time the overlap failed to hide, so e2e_wave_s
    stays an honest sustained-period measure; commit_overlap_s reports
    the hidden portion per wave."""
    from swarmkit_tpu.ops.pipeline import TickPipeline
    from swarmkit_tpu.ops.resident import ResidentPlacement
    from swarmkit_tpu.scheduler.encode import IncrementalEncoder

    waves = max(waves, depth + 2)
    rng = random.Random(7)
    infos = _mk_nodes(rng, n_nodes, plugin_every=plugin_every)

    # compile warm-up on a throwaway encoder/state (cache is process-wide)
    enc_w = IncrementalEncoder()
    rp_w = ResidentPlacement(enc_w)
    t0 = time.perf_counter()
    _tick(enc_w, rp_w, infos, _mk_groups(rng, n_tasks, n_services, wave=0,
                                         **kw), batch, np)
    compile_s = time.perf_counter() - t0

    # tracked=True (round 6, matching the production Scheduler): steady
    # waves take the encoder's ZERO-SCAN fast path (no marks pending —
    # the pipeline's restamp keeps fingerprints reconciled without a
    # feed) and the O(1) clean gate lets the async plane OVERLAP the
    # heavy commit with the next wave's encode+dispatch. The cold tick
    # still pays a full scan (the initial set-changed mark).
    enc = IncrementalEncoder(tracked=True)
    rp = ResidentPlacement(enc)
    # Scheduler(backend="auto") cold-start policy: below COLD_CPU_NODES
    # the first wave runs on the CPU oracle (cheaper than a blocking
    # cold upload + counts RTT through the tunnel); the device warms on
    # the next wave's dispatch. The bench's cold tick mirrors whichever
    # path production takes at this shape.
    from swarmkit_tpu.scheduler.scheduler import COLD_CPU_NODES
    cold_policy_cpu = n_nodes <= COLD_CPU_NODES
    if cold_policy_cpu:
        groups1 = _mk_groups(rng, n_tasks, n_services, wave=1, **kw)
        t0 = time.perf_counter()
        p1 = enc.encode(infos, groups1)
        encode1_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        counts1 = batch.cpu_schedule_encoded(p1)
        fill1_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        batch.materialize_orders(p1, counts1)
        mat1_s = time.perf_counter() - t0
        policy_tick = encode1_s + fill1_s + mat1_s
        cold = {
            "problem": p1, "counts": counts1, "parity": True,
            "tpu_tick_s": policy_tick,      # what production pays cold
            "cpu_tick_s": policy_tick,
            "device_s": 0.0, "encode_s": encode1_s,
            "materialize_s": mat1_s, "cpu_fill_s": fill1_s,
            "placed": int(counts1.sum()),
        }
        rp.invalidate()
    else:
        cold = _tick(enc, rp, infos, _mk_groups(rng, n_tasks, n_services,
                                                wave=1, **kw), batch, np)
    parity = cold["parity"]
    _apply_wave(enc, rp, infos, cold["problem"], cold["counts"], batch)

    wave_groups = [_mk_groups(rng, n_tasks, n_services, wave=2 + w, **kw)
                   for w in range(waves)]

    by_node = {i.node.id: i for i in infos}
    commit_phases = []                      # per wave: (materialize_s, add_s)

    def commit(p, counts):
        # the production commit shape (_apply_decisions): slot orders, then
        # the group's id-sorted tasks zip with them, bulked per
        # (node, shared-spec) cell like the scheduler's commit
        t0 = time.perf_counter()
        orders = batch.materialize_orders(p, counts)
        mat_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        infos_arr = [by_node[nid] for nid in p.node_ids]
        n_added = batch.apply_wave(infos_arr, p.groups, orders)
        assert n_added == int(counts.sum())
        commit_phases.append((mat_s, time.perf_counter() - t0))

    # (waves was clamped to >= depth + 2 above: steady sampling needs a
    # fully-pipelined wave — the fill-in phase's pulls have no commit
    # window under them)
    pipe = TickPipeline(enc, rp, commit, depth=depth,
                        async_commit=async_commit)
    delta_rows_mark = None
    done = []
    import gc
    try:
        for w in range(waves):
            # a production scheduler collects in its idle debounce window
            # between ticks, not inside the commit: without this, gen-2
            # pauses from the accumulated wave objects land mid-wall and
            # randomize the commit phase by 1.5-2x (both backends' commit
            # is identical, so this only de-noises the comparison)
            gc.collect()
            done.extend(pipe.tick(infos, wave_groups[w]))
            if w == 0:
                delta_rows_mark = rp.uploads_delta_rows
        done.extend(pipe.flush())
    finally:
        pipe.close()
    assert len(done) == waves and not any(
        t["serial_fallback"] for t in pipe.timings)

    # parity: every steady wave bit-identical to the oracle on the same
    # emitted problem (the snapshot the device scheduled against)
    for p, counts in done:
        parity = parity and bool(
            (counts == batch.cpu_schedule_encoded(p)).all())
    p_last, c_last = done[-1]
    orders = batch.materialize_orders(p_last, c_last)
    cpu_orders = batch.materialize_orders(
        p_last, batch.cpu_schedule_encoded(p_last))
    parity = parity and all(
        np.array_equal(a, b) for a, b in zip(orders, cpu_orders))

    # classic decomposition per steady wave w: encode/dispatch live in
    # timings[w], its pull residual + fold in timings[w + depth] (wave w
    # completes when the pipe is `depth` deep past it — either a later
    # tick or a flush entry), its commit phases in commit_phases[w]
    T = pipe.timings
    per_wave = []
    for w in range(waves):
        mat_s, add_s = commit_phases[w]
        dev = T[w]["dispatch_s"] + T[w + depth]["pull_s"]
        per_wave.append({
            "tick": T[w]["encode_s"] + dev + mat_s,
            "encode": T[w]["encode_s"], "device": dev, "mat": mat_s,
            "add": add_s, "fold": T[w + depth]["fold_s"],
            "dirty_scan": T[w].get("dirty_scan_s", 0.0),
        })

    # async plane observability: wave w's heavy commit is worker job w
    # (submitted at tick w+depth; the final `depth` waves commit inline
    # in flush); the unhidden residual shows as tick w+depth+1's barrier
    # wait. overlap = heavy − barrier = commit time the plane removed
    # from the wave period.
    overlap = []
    if async_commit and pipe.worker is not None:
        job_s = pipe.worker.job_s
        for w in range(min(len(job_s), waves - depth - 1)):
            barrier = T[w + depth + 1]["barrier_s"]
            overlap.append(max(0.0, job_s[w] - barrier))
    best_w = min(range(waves), key=lambda w: per_wave[w]["tick"])
    best = per_wave[best_w]
    cpu_fill_s, cpu_counts = best_of(
        lambda: batch.cpu_schedule_encoded(done[best_w][0]), 2)
    cpu_tick_s = best["encode"] + cpu_fill_s + best["mat"]

    # full pipelined periods: ticks depth+1..waves-1 each cover one whole
    # steady wave (pull+fold+commit of the oldest in-flight, encode+
    # dispatch of the next). Earlier ticks are excluded: their pulls are
    # fill-in-phase waves whose transfers had no commit window under
    # them, so including them would report a serial period as the
    # pipelined number.
    e2e = [T[w]["wall_s"] for w in range(depth + 1, waves)]
    e2e_wave_s = min(e2e)
    cpu_e2e_wave_s = cpu_tick_s + best["add"] + best["fold"]

    kernel_resident_s = _probe_resident_kernel(done[best_w][0],
                                               placement_ops)
    return {
        "compile_s": round(compile_s, 2),
        "tpu_tick_s": round(best["tick"], 4),
        "cpu_tick_s": round(cpu_tick_s, 4),
        "device_s": round(best["device"], 5),
        "kernel_resident_s": round(kernel_resident_s, 6),
        "cpu_fill_s": round(cpu_fill_s, 4),
        "encode_s": round(best["encode"], 4),
        "materialize_s": round(best["mat"], 4),
        "e2e_wave_s": round(e2e_wave_s, 4),
        "cpu_e2e_wave_s": round(cpu_e2e_wave_s, 4),
        "e2e_speedup": round(cpu_e2e_wave_s / e2e_wave_s, 2),
        # per-stage HOST columns (ISSUE 6): where the steady wave's host
        # tail went — the encoder's dirty scan (~0 on the tracked
        # zero-scan path) and the write-back half of the commit (the
        # add_task walk; the store tx in production rides the same walk)
        "dirty_scan_s": round(best["dirty_scan"], 5),
        "writeback_s": round(best["add"], 4),
        # waves whose heavy commit overlapped the next encode+dispatch
        # (the round-6 encode/commit overlap; 0 in sync mode)
        "overlapped_waves": sum(
            1 for t in T if t.get("commit_overlapped")),
        "zero_scan_encodes": int(waves + 1 - enc.fp_scans),
        "commit_async": bool(async_commit),
        # commit seconds the async plane hid under the next wave's
        # dispatch/pull per steady wave (empty list in sync mode)
        "commit_overlap_s": (round(sum(overlap) / len(overlap), 4)
                             if overlap else None),
        "all_commit_overlap_s": [round(o, 4) for o in overlap],
        "all_barrier_s": [round(t.get("barrier_s", 0.0), 4) for t in T],
        "cold_tpu_tick_s": round(cold["tpu_tick_s"], 4),
        "cold_cpu_tick_s": round(cold["cpu_tick_s"], 4),
        "cold_device_s": round(cold["device_s"], 4),
        # which path the auto backend's cold-start policy takes at this
        # shape; with "cpu" the device-warming upload cost shows up as
        # the first pipeline wave's dispatch instead (warmup_dispatch_s)
        "cold_backend": "cpu" if cold_policy_cpu else "device",
        "warmup_dispatch_s": round(T[0]["dispatch_s"], 4),
        "speedup": round(cpu_tick_s / best["tick"], 2),
        "cold_speedup": round(cold["cpu_tick_s"] / cold["tpu_tick_s"], 2),
        # None when the probe's subtraction bottoms out (sub-jitter kernel
        # at small shapes: K dispatches cost no more than the sync alone)
        "device_vs_kernel_x": (round(best["device"] / kernel_resident_s, 1)
                               if kernel_resident_s > 0 else None),
        # marginal rate across fully-steady ticks: excludes the first
        # steady dispatch, which ships the cold wave's correction burst
        "delta_rows_per_steady_tick": (
            (rp.uploads_delta_rows - delta_rows_mark) // max(1, waves - 1)),
        "full_uploads": rp.uploads_full,
        "parity": parity,
        "placed": int(c_last.sum()),
        "all_steady_tpu_s": [round(pw["tick"], 4) for pw in per_wave],
        "all_e2e_wave_s": [round(t, 4) for t in e2e],
    }


def bench_global_diff(np):
    """Desired-vs-actual reconcile, device-resident and O(churn): the
    eligibility matrix, task→node table, and per-(service, node) task
    counts live on device; a steady round uploads only the churned slots
    (1% task moves) and pulls decisions for the touched pairs only —
    everything else is unchanged by construction. Rounds run in bursts of
    16 with one host sync, as the global orchestrator debounces reconcile
    passes (manager/orchestrator/global/global.go event batching). The
    CPU baseline runs the framework's numpy diff per round."""
    import jax
    from swarmkit_tpu.ops.reconcile import (
        global_diff_churn_burst,
        global_diff_np,
        pack_eligibility,
        task_count_flat,
        unpack_eligibility,
    )

    rng = np.random.default_rng(0)
    S, N, T = 200, 50_000, 2_000     # 10M (service, node) pairs
    eligible = rng.random((S, N)) < 0.04
    # converged start: every service's tasks sit on its eligible nodes
    task_nodes = np.full((S, T), -1, np.int32)
    for si in range(S):
        elig_nodes = np.flatnonzero(eligible[si])
        k = min(T, elig_nodes.size)
        task_nodes[si, :k] = elig_nodes[:k]

    # warm the unpack/count programs on same-shape throwaways: a daemon
    # compiles once at startup, not per cold contact, and cold_h2d_s is
    # defined as the state-resident cost (compile is its own metric in
    # the scheduler rows)
    import jax.numpy as jnp
    probe = jax.jit(lambda e, c: e[0, 0].astype(jnp.int32) + c[0])
    warm = unpack_eligibility(
        jax.device_put(np.zeros((S, (N + 7) // 8), np.uint8)), N)
    warm2 = task_count_flat(jax.device_put(np.zeros((S, T), np.int32)), N)
    int(np.asarray(probe(warm, warm2)))

    # cold contact: the [S, N] bool eligibility ships BIT-PACKED (8x
    # fewer wire bytes through the single-digit-MB/s tunnel — the same
    # move as the resident svc-matrix fix) and unpacks device-side; the
    # sync is a true value pull (block_until_ready lies through the
    # tunnel)
    t0 = time.perf_counter()
    packed_dev = jax.device_put(pack_eligibility(eligible))
    tn_dev = jax.device_put(task_nodes)
    elig_dev = unpack_eligibility(packed_dev, N)
    cnt_dev = task_count_flat(tn_dev, N)
    int(np.asarray(probe(elig_dev, cnt_dev)))   # syncs BOTH upload chains
    h2d_s = time.perf_counter() - t0

    U = S * T // 100                       # 1% churn per round
    BURST = 16

    def mk_upd(rnd):
        # unique (service, slot) per round: a task moves once per round
        flat = rnd.choice(S * T, U, replace=False)
        return ((flat // T).astype(np.int32), (flat % T).astype(np.int32),
                rnd.integers(-1, N, U).astype(np.int32))

    upds = [mk_upd(rng) for _ in range(BURST)]
    rows_b = np.stack([u[0] for u in upds])
    cols_b = np.stack([u[1] for u in upds])
    vals_b = np.stack([u[2] for u in upds])
    out = global_diff_churn_burst(elig_dev, tn_dev, cnt_dev,
                                  rows_b, cols_b, vals_b)   # compile
    jax.block_until_ready(out)

    burst_s = None
    codes = None
    for _ in range(6):
        # restart from the converged state; ONE upload, ONE device
        # program, ONE sync per burst (the uint8 code pull)
        t0 = time.perf_counter()
        _, _, codes_dev = global_diff_churn_burst(
            elig_dev, tn_dev, cnt_dev, rows_b, cols_b, vals_b)
        codes = np.asarray(codes_dev)
        dt = time.perf_counter() - t0
        burst_s = dt if burst_s is None or dt < burst_s else burst_s
    round_s = burst_s / BURST

    # CPU: same churn, per-round decision availability via the numpy diff
    tn_np = task_nodes.copy()
    t0 = time.perf_counter()
    for upd in upds:
        tn_np[upd[0], upd[1]] = upd[2]
        c_np, s_np = global_diff_np(eligible, tn_np)
    cpu_s = (time.perf_counter() - t0) / BURST

    # parity: at every touched pair of the LAST round, the incremental
    # bits must equal the full diff of the final state. The pair
    # coordinates come from the HOST's own view (it knows each moved
    # task's old and new node), matching the production consumer.
    tn_prev = task_nodes.copy()
    for upd in upds[:-1]:
        tn_prev[upd[0], upd[1]] = upd[2]
    r_last, c_last, v_last = upds[-1]
    old_nodes = tn_prev[r_last, c_last]
    pair_nodes = np.concatenate([np.maximum(old_nodes, 0),
                                 np.maximum(v_last, 0)])
    pair_svcs = np.concatenate([r_last, r_last])
    last = codes[-1]
    ok = True
    for s, n, code in zip(pair_svcs.tolist(), pair_nodes.tolist(),
                          last.tolist()):
        if code & 4 and (bool(c_np[s, n]) != bool(code & 1)
                         or bool(s_np[s, n]) != bool(code & 2)):
            ok = False
            break
    return {"pairs": S * N, "churn_slots": U, "burst": BURST,
            "tpu_round_s": round(round_s, 5),
            "cold_h2d_s": round(h2d_s, 4), "cpu_s": round(cpu_s, 5),
            "speedup_with_upload": round(cpu_s / round_s, 3),
            "parity": bool(ok)}


def bench_raft_replay(np):
    """1M-entry × 5-manager quorum tally + commit-frontier advance, device-
    resident: the ack matrix lives on device and each round uploads only
    the per-manager durable frontiers (20 bytes). Rounds run in bursts of
    16 advances per commit read — the applier consumes the commit index
    batch-wise, exactly like the reference's Ready/Advance batching
    (etcd raft releases appliers once per Ready, not per ack)."""
    import jax
    import jax.numpy as jnp
    from swarmkit_tpu.ops.raft_replay import (
        frontier_advance_burst,
        replay_commit,
        unpack_acks,
    )

    rng = np.random.default_rng(1)
    M, E = 5, 1_000_000
    acks = np.zeros((M, E), bool)
    frontier = rng.integers(E // 2, E, M).astype(np.int32)
    for m in range(M):
        acks[m, :frontier[m]] = True
    quorum = M // 2 + 1

    # warm the unpack/tally programs (compile is paid once per daemon,
    # not per cold contact; the scheduler rows report compile separately)
    warm = unpack_acks(
        jax.device_put(np.zeros((M, (E + 7) // 8), np.uint8)), E)
    probe = jax.jit(lambda a: a[0, 0].astype(jnp.int32))
    int(np.asarray(probe(warm)))

    # cold contact: the [M, E] bool ack matrix ships BIT-PACKED (8x fewer
    # wire bytes) and unpacks device-side; true value-pull sync
    # (block_until_ready lies through the tunnel)
    from swarmkit_tpu.ops.bitpack import pack_bits

    t0 = time.perf_counter()
    packed = pack_bits(acks)
    acks_dev = unpack_acks(jax.device_put(packed), E)
    int(np.asarray(probe(acks_dev)))
    h2d_s = time.perf_counter() - t0

    commit, _ = replay_commit(acks_dev, quorum)               # compile
    int(np.asarray(commit))

    BURST, N_BURSTS, DEPTH = 16, 4, 2
    f = frontier
    bursts = []
    for _ in range(N_BURSTS):
        rounds = []
        for _ in range(BURST):
            f = np.minimum(f + rng.integers(0, 1000, M),
                           E - 1).astype(np.int32)
            rounds.append(f)
        bursts.append(np.stack(rounds))                       # [B, M]
    # compile on a throwaway output — reassigning acks_dev here would
    # bake burst 0 into the timing loop's start state and skew the
    # per-round commit parity below
    _warm_acks, _warm_commits = frontier_advance_burst(
        acks_dev, bursts[0], quorum)
    int(np.asarray(_warm_commits[-1]))
    del _warm_acks, _warm_commits

    # steady state, Ready/Advance-shaped: each burst is ONE [B, M] upload
    # + ONE scan dispatch + ONE per-round commit-index pull, and the pull
    # rides the link under the next DEPTH bursts' dispatches (the applier
    # consumes commit indices a couple of batches behind the appender,
    # exactly like the scheduler pipeline hides its counts D2H)
    from collections import deque
    all_commits = None
    burst_s = None
    for _ in range(6):
        a_dev = acks_dev
        pending: deque = deque()
        got = []
        t0 = time.perf_counter()
        for fb in bursts:
            a_dev, commits = frontier_advance_burst(a_dev, fb, quorum)
            try:
                commits.copy_to_host_async()
            except Exception:
                pass
            pending.append(commits)
            if len(pending) > DEPTH:
                got.append(np.asarray(pending.popleft()))
        while pending:
            got.append(np.asarray(pending.popleft()))
        dt = time.perf_counter() - t0
        if burst_s is None or dt < burst_s:
            burst_s = dt
            all_commits = np.concatenate(got)
    round_s = burst_s / (BURST * N_BURSTS)

    # CPU: same advances on the ack-matrix representation, tally per round
    # (its commit must be current after each round too)
    acks_np = acks.copy()
    cpu_commits = []
    t0 = time.perf_counter()
    for fb in bursts:
        for fr in fb:
            for m in range(M):
                acks_np[m, :fr[m]] = True
            tally = acks_np.sum(axis=0)
            comm = tally >= quorum
            cpu_commits.append(int(np.cumprod(comm).sum()))
    cpu_s = (time.perf_counter() - t0) / (BURST * N_BURSTS)

    final_commit = int(all_commits[-1])
    expected = int(np.sort(bursts[-1][-1])[M - quorum])
    # parity: EVERY round's commit index, not just the last
    ok = (all_commits.tolist() == cpu_commits
          and final_commit == expected)
    return {"entries": E, "managers": M, "commit_index": final_commit,
            "burst": BURST, "bursts_in_flight": DEPTH,
            "tpu_round_s": round(round_s, 6), "cold_h2d_s": round(h2d_s, 4),
            "cpu_s": round(cpu_s, 5),
            "speedup_with_upload": round(cpu_s / round_s, 3),
            "parity": bool(ok)}


def bench_raft_backed_store(np):
    """Group-commit plane end to end: a REAL 3-manager in-process raft
    cluster (worker threads + 10 ms ticker, segmented WAL on disk) behind
    a replicated MemoryStore. Measures propose throughput blocking
    (depth 1: one store.update per quorum round trip, the pre-round-6
    write path) vs pipelined (store.batch pipeline_depth 16/64 riding
    propose_async), plus the amortized fsyncs-per-commit on the leader —
    the group-commit plane's whole point is driving that below one."""
    import os
    import shutil
    import tempfile
    import threading

    from swarmkit_tpu.api.objects import Task
    from swarmkit_tpu.raft.proposer import RaftProposer
    from swarmkit_tpu.raft.storage import RaftStorage
    from swarmkit_tpu.raft.testutils import RaftCluster
    from swarmkit_tpu.store.memory import MemoryStore

    tmp = tempfile.mkdtemp(prefix="swarmkit-raft-bench-")
    storages = {i: RaftStorage(os.path.join(tmp, str(i)))
                for i in (1, 2, 3)}
    c = RaftCluster(3, storages=storages)
    stores = {}
    for i, node in c.nodes.items():
        p = RaftProposer(node)
        st = MemoryStore(proposer=p)
        p.attach_store(st)
        stores[i] = st
    for n in c.nodes.values():
        n.start()
    stop = threading.Event()

    def ticker():
        # the daemon's REAL tick cadence (0.2 s): election timeout 2-4 s,
        # CheckQuorum lease window 2 s. A faster bench tick narrows the
        # lease below what GIL/fsync scheduling gaps on a 1-core host can
        # guarantee and churns elections mid-measurement (the daemon's
        # ticker also has a catch-up cap for burst protection)
        while not stop.is_set():
            for n in c.nodes.values():
                n.tick()
            time.sleep(0.2)

    tk = threading.Thread(target=ticker, daemon=True, name="raft-bench-tick")
    tk.start()
    try:
        from swarmkit_tpu.raft.proposer import ProposeError

        def current():
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                leaders = [n for n in c.nodes.values() if n.is_leader]
                if leaders:
                    lead = max(leaders, key=lambda n: n.term)
                    return lead, stores[lead.id], storages[lead.id]
                time.sleep(0.02)
            raise RuntimeError("no leader elected")

        def attempt(fn, tries=5):
            """Run one measured segment against the current leader; a
            leadership change mid-segment (election churn on a loaded
            1-core host) re-resolves and re-measures, like a forwarding
            client would."""
            for a in range(tries):
                leader, store, lst = current()
                try:
                    return fn(a, leader, store, lst)
                except ProposeError:
                    time.sleep(1.0)
            raise RuntimeError("raft bench: leadership too unstable")

        def create(tx, tid):
            t = Task(id=tid, service_id="svc")
            tx.create(t)

        row = {"managers": 3}

        # depth 1: the blocking write path, one fsync + one quorum RTT each
        n1 = 200

        def blocking(a, leader, store, lst):
            f0 = lst.wal_fsyncs + lst.meta_fsyncs
            c0 = leader.commits_applied
            t0 = time.perf_counter()
            for k in range(n1):
                store.update(lambda tx, k=k: create(tx, f"d1-{a}-{k}"))
            dt = time.perf_counter() - t0
            fsyncs = (lst.wal_fsyncs + lst.meta_fsyncs) - f0
            commits = leader.commits_applied - c0
            row["blocking_n"] = n1
            row["blocking_per_s"] = round(n1 / dt, 1)
            row["blocking_fsyncs_per_commit"] = round(
                fsyncs / max(1, commits), 3)

        attempt(blocking)

        def pipelined(depth, n):
            def run(a, leader, store, lst):
                def fill(b):
                    for k in range(n):
                        b.update(lambda tx, k=k:
                                 create(tx, f"d{depth}-{a}-{k}"))
                        b._flush()      # one proposal per sub-transaction
                f0 = lst.wal_fsyncs + lst.meta_fsyncs
                c0 = leader.commits_applied
                t0 = time.perf_counter()
                store.batch(fill, pipeline_depth=depth)
                dt = time.perf_counter() - t0
                fsyncs = (lst.wal_fsyncs + lst.meta_fsyncs) - f0
                commits = leader.commits_applied - c0
                row[f"d{depth}_per_s"] = round(n / dt, 1)
                row[f"d{depth}_fsyncs_per_commit"] = round(
                    fsyncs / max(1, commits), 3)
            attempt(run)

        pipelined(16, 1_000)
        pipelined(64, 2_000)
        row["speedup_d64_vs_blocking"] = round(
            row["d64_per_s"] / row["blocking_per_s"], 2)

        # parity = replication correctness: every replica converges to the
        # SAME task set with identical versions (speed is reported, not
        # gated — the judged property is that group commit changed no
        # semantics). Retried segments may leave extra tasks; identity
        # across replicas is what matters.
        def contents():
            return {
                i: tuple(sorted((t.id, t.meta.version.index)
                                for t in st.view().find_tasks()))
                for i, st in stores.items()
            }

        deadline = time.monotonic() + 30
        snap = contents()
        while time.monotonic() < deadline:
            if len(set(snap.values())) == 1:
                break
            time.sleep(0.1)
            snap = contents()
        row["tasks_replicated"] = len(snap[1])
        row["parity"] = len(set(snap.values())) == 1 and \
            len(snap[1]) >= n1 + 3_000
        return row
    finally:
        stop.set()
        tk.join(timeout=2)
        for n in c.nodes.values():
            n.stop()
        shutil.rmtree(tmp, ignore_errors=True)


def bench_e2e_service_start(np):
    """The swarm-bench scenario (reference cmd/swarm-bench/benchmark.go:
    38-71 + collector.go): a real in-process cluster — 3 managers over
    TCP+mTLS raft, 5 workers — runs a 100-replica service; per-task
    time-to-RUNNING percentiles are read from the replicated store (the
    reference has containers phone home over UDP; the store's observed
    RUNNING timestamps carry the same signal). Control-plane wall clock,
    not kernel math: the auto backend keeps 100×8 ticks on CPU."""
    import tempfile
    import pathlib
    import shlex

    sys.path.insert(0, "tests")
    from test_integration_cluster import Cluster
    from test_scheduler import wait_for

    from swarmkit_tpu.api.specs import (Annotations, ContainerSpec,
                                        ServiceSpec, TaskSpec)
    from swarmkit_tpu.api.types import TaskState
    from swarmkit_tpu.store import by

    base = pathlib.Path(tempfile.mkdtemp(prefix="bench-e2e-"))
    cluster = Cluster(base)
    try:
        for _ in range(3):
            cluster.add_manager()
        for _ in range(5):
            cluster.add_agent()
        leader = cluster.leader()
        assert wait_for(
            lambda: len([n for n in leader.store.view(
                lambda tx: tx.find_nodes())]) == 8, timeout=60)

        REPLICAS = 100
        ctl = cluster.control()
        t0_wall = time.time()
        t0 = time.monotonic()
        svc = ctl.create_service(ServiceSpec(
            annotations=Annotations(name="bench-e2e"),
            replicas=REPLICAS,
            task=TaskSpec(runtime=ContainerSpec(
                command=shlex.split("sleep 3600")))))
        # per-task latency from the task's own observed-RUNNING status
        # timestamp (written by the status write-back path) — not the poll
        # clock, whose 50 ms cadence would quantize the percentiles
        seen: dict[str, float] = {}
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline and len(seen) < REPLICAS:
            tasks = leader.store.view(
                lambda tx: tx.find_tasks(by.ByServiceID(svc.id)))
            for t in tasks:
                if t.id not in seen and t.status.state == TaskState.RUNNING:
                    seen[t.id] = t.status.timestamp - t0_wall
            time.sleep(0.05)
        all_running_s = (time.monotonic() - t0
                         if len(seen) == REPLICAS else None)
        ctl.close()

        diagnosis = None
        if len(seen) < REPLICAS:
            # progressive-collector spirit (reference cmd/swarm-bench/
            # collector.go): a stalled run must say WHERE the tasks sit,
            # not just that 0/N ran
            diagnosis = _diagnose_e2e_stall(leader, svc.id)

        lat = sorted(seen.values())

        def pct(p):
            # the ONE nearest-rank implementation (utils/slo.py, shared
            # with swarmbench; the naive int(p/100*n) index reported
            # p100 as p99 at n=100)
            from swarmkit_tpu.utils.slo import quantile_nearest_rank

            v = quantile_nearest_rank(lat, p)
            return None if v is None else round(v, 3)

        row = {
            "managers": 3, "workers": 5, "replicas": REPLICAS,
            "running": len(seen),
            "p50_s": pct(50), "p90_s": pct(90), "p99_s": pct(99),
            "all_running_s": round(all_running_s, 3)
            if all_running_s is not None else None,
            "parity": len(seen) == REPLICAS,
        }
        if diagnosis is not None:
            row["diagnosis"] = diagnosis
        return row
    finally:
        cluster.stop_all()


def _diagnose_e2e_stall(leader, service_id):
    """TaskState census + node states + stuck-task samples for a stalled
    e2e row, read from the leader's replicated store. Keeps a red row
    self-explanatory instead of `running: 0` with no trail (the round-3
    artifact's failure mode)."""
    from collections import Counter

    from swarmkit_tpu.store import by

    diag = {}
    try:
        tasks = leader.store.view(
            lambda tx: tx.find_tasks(by.ByServiceID(service_id)))
        census = Counter(t.status.state.name for t in tasks)
        diag["task_state_census"] = dict(census)
        diag["task_total"] = len(tasks)
        # sample the least-advanced tasks: their err/message is where the
        # pipeline says why it stopped
        stuck = sorted(tasks, key=lambda t: int(t.status.state))[:5]
        diag["stuck_samples"] = [{
            "id": t.id, "state": t.status.state.name,
            "desired": t.desired_state.name, "node_id": t.node_id,
            "err": t.status.err, "message": t.status.message,
        } for t in stuck]
    except Exception as exc:                       # pragma: no cover
        diag["task_census_error"] = repr(exc)
    try:
        nodes = leader.store.view(lambda tx: tx.find_nodes())
        diag["node_state_census"] = dict(Counter(
            n.status.state.name for n in nodes))
    except Exception as exc:                       # pragma: no cover
        diag["node_census_error"] = repr(exc)
    try:
        import threading
        diag["live_threads"] = sorted({t.name.split("-")[0]
                                       for t in threading.enumerate()})[:20]
    except Exception:                              # pragma: no cover
        pass
    return diag


def bench_dispatcher_fanout(np, n_nodes=10_000):
    """VERDICT item 7: the assignment-diff plane at 10k registered
    sessions (reference manager/dispatcher/dispatcher.go:1013-1207).
    One service-wide update (every task of the service re-written in a
    single store transaction) dirties all 10k nodes; measured: commit →
    every session's incremental assignment message enqueued and drained
    through the existing 100ms/10k-item batching."""
    from swarmkit_tpu.api.objects import Node, Task
    from swarmkit_tpu.api.types import NodeStatusState, TaskState
    from swarmkit_tpu.dispatcher.dispatcher import Dispatcher
    from swarmkit_tpu.store.memory import MemoryStore

    store = MemoryStore()

    def seed(tx):
        for i in range(n_nodes):
            n = Node(id=f"fn{i:05d}")
            n.status.state = NodeStatusState.READY
            tx.create(n)
            t = Task(id=f"ft{i:05d}", service_id="fansvc",
                     node_id=n.id, slot=i + 1)
            t.status.state = TaskState.RUNNING
            t.desired_state = TaskState.RUNNING
            tx.create(t)
    store.update(seed)

    d = Dispatcher(store, heartbeat_period=120.0)
    d.start()
    try:
        t0 = time.perf_counter()
        sessions = [(f"fn{i:05d}", d.register(f"fn{i:05d}"))
                    for i in range(n_nodes)]
        register_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        channels = [d.assignments(nid, sid) for nid, sid in sessions]
        subscribe_s = time.perf_counter() - t0
        for ch in channels:                      # drain COMPLETE snapshots
            # registration itself pre-dirties the node (re-registering
            # agents get fresh state), so a stray incremental may sit
            # ahead of the COMPLETE — skip to it
            msg = ch.try_get()
            while msg is not None and msg.type != "complete":
                msg = ch.try_get()
            assert msg is not None and msg.type == "complete"

        # THE measured number: one service update → all incrementals
        def touch(tx):
            for i in range(n_nodes):
                cur = tx.get_task(f"ft{i:05d}").copy()
                cur.annotations.labels = {"rev": "2"}
                tx.update(cur)
        t0 = time.perf_counter()
        store.update(touch)
        got = 0
        deadline = time.monotonic() + 600
        for ch in channels:
            # the batch flush serves dirty sessions in SET order, so any
            # given channel may be served late in the 10k sweep — wait
            # against the overall deadline, not per channel
            while time.monotonic() < deadline:
                try:
                    msg = ch.get(timeout=2)
                except TimeoutError:
                    continue
                if msg is not None and msg.type == "incremental" \
                        and msg.changes:
                    got += 1
                    break
        fanout_s = time.perf_counter() - t0

        # ---- rollout-storm sub-row (ISSUE 4): N services updated, a
        # FRACTION of nodes dirtied — the fan-out plane's design case.
        # Reported per flush: store transactions (shared snapshot → 1)
        # and wire copies per shipped assignment (copy-on-ship → 1.0);
        # the old plane paid 2 tx per dirty NODE and copied every
        # relevant object per dirty node whether or not it shipped.
        storm_nodes = max(1, n_nodes // 10)
        m0 = dict(d.metrics)

        def storm(tx):
            for i in range(storm_nodes):
                cur = tx.get_task(f"ft{i:05d}").copy()
                cur.annotations.labels = {"rev": "3"}
                tx.update(cur)
        t0 = time.perf_counter()
        store.update(storm)
        storm_got = 0
        deadline = time.monotonic() + 600
        for ch in channels[:storm_nodes]:
            while time.monotonic() < deadline:
                try:
                    msg = ch.get(timeout=2)
                except TimeoutError:
                    continue
                if msg is not None and msg.type == "incremental" \
                        and msg.changes:
                    storm_got += 1
                    break
        storm_s = time.perf_counter() - t0
        dm = {k: d.metrics[k] - m0[k] for k in
              ("flushes", "flush_tx", "wire_copies", "ships")}
        return {
            "sessions": n_nodes,
            "register_s": round(register_s, 2),
            "subscribe_s": round(subscribe_s, 2),
            "fanout_drain_s": round(fanout_s, 3),
            "msgs_per_s": round(got / fanout_s) if fanout_s else None,
            "delivered": got,
            "storm": {
                "services_updated": storm_nodes,
                "nodes_dirtied_frac": round(storm_nodes / n_nodes, 3),
                "drain_s": round(storm_s, 3),
                "flush_latency_s": round(d.metrics["last_flush_s"], 4),
                "store_tx_per_flush": round(
                    dm["flush_tx"] / dm["flushes"], 3)
                if dm["flushes"] else None,
                "copies_per_ship": round(
                    dm["wire_copies"] / dm["ships"], 3)
                if dm["ships"] else None,
                "delivered": storm_got,
            },
            "parity": got == n_nodes and storm_got == storm_nodes,
        }
    finally:
        d.stop()


def bench_dispatcher_fanout_storm(np, n_sessions=100_000,
                                  shard_counts=(1, 4, 8),
                                  beats_sample=20_000,
                                  follower_reads=None,
                                  ceiling_sessions=1_000_000,
                                  ceiling_shards=(1, 4)):
    """ISSUE 13: the SHARDED fan-out plane at a 100k-session storm.

    Driven (no dispatcher thread): sessions are injected directly (the
    row measures the flush plane, not `register`'s store write), every
    session is primed with a COMPLETE via one sharded flush, then one
    service-wide update dirties all of them and ONE flush serves the
    whole storm. Per-shard columns at each P: flush wall time,
    store-tx-per-flush (the judged 1.0, GLOBAL — the snapshot is shared
    read-only across shards), dirty-walks-per-shard (≤ 1.0), p50/p99
    heartbeat beat latency over a sample (the sharded wheel + per-shard
    jitter rng path), and messages delivered. A follower read-plane
    slice serves `follower_reads` lease-gated read streams from the
    same store (stub lease: this is a one-process bench) and reports
    `follower_read_ratio` = follower-served / total read streams.

    ISSUE 16 grows two legs: a `diff_plane` block — the columnar
    zero-delta gate (P=4) against a single-plane dict oracle on the
    same store, with sampled wire parity on a real storm — and a
    `serve_ceiling` block: an honest `ceiling_sessions`-session serve
    storm (capped per-session channel buffers — the 1M OOM was queued
    wire copies) measuring where the GIL binds: the dict serve walk is
    pure Python, so shard-pool speedup flattens near 1.0 while the
    gate's numpy pass keeps scaling by SKIPPING.

    tests/test_bench_diag.py pins a reduced CPU-smoke shape of this
    row's op-count contracts."""
    from swarmkit_tpu.api.objects import Node, Task
    from swarmkit_tpu.api.types import NodeStatusState, TaskState
    from swarmkit_tpu.dispatcher.dispatcher import Dispatcher, Session
    from swarmkit_tpu.dispatcher.follower import FollowerReadPlane
    from swarmkit_tpu.store.memory import MemoryStore
    from swarmkit_tpu.store.watch import Channel
    from swarmkit_tpu.utils.slo import quantiles_nearest_rank

    if follower_reads is None:
        follower_reads = max(1, n_sessions // 10)
    store = MemoryStore()

    def seed(tx):
        for i in range(n_sessions):
            n = Node(id=f"sf{i:06d}")
            n.status.state = NodeStatusState.READY
            tx.create(n)
            t = Task(id=f"st{i:06d}", service_id="stormsvc",
                     node_id=n.id, slot=i + 1)
            t.status.state = TaskState.RUNNING
            t.desired_state = TaskState.RUNNING
            tx.create(t)
    store.update(seed)
    node_ids = [f"sf{i:06d}" for i in range(n_sessions)]

    per_shard = {}
    rev = 0
    for P in shard_counts:
        d = Dispatcher(store, heartbeat_period=120.0,
                       rate_limit_period=-1.0, shards=P, jitter_seed=13)
        try:
            # inject sessions (no store write, no wheel arm: liveness is
            # not this row; beats below go through the full heartbeat
            # path against explicitly-armed wheel entries)
            grace = d.heartbeat_period * 3
            for nid in node_ids:
                s = Session(node_id=nid, session_id=f"b.{nid}",
                            channel=Channel(matcher=None, limit=None))
                d._sessions[nid] = s
                d._hb_wheel.add(nid, grace, lambda: None)
            # prime: one flush serves every session its first diff
            d._mark_dirty_many(node_ids)
            t0 = time.perf_counter()
            d._send_incrementals()
            prime_s = time.perf_counter() - t0
            for nid in node_ids:     # drain the prime diffs: the storm
                ch = d._sessions[nid].channel   # count below must see
                while ch.try_get() is not None:  # ONLY storm messages
                    pass

            # beat storm sample: p50/p99 latency of the full heartbeat
            # path (session check + sharded wheel beat + shard-rng jitter)
            lat = []
            for i in range(min(beats_sample, n_sessions)):
                nid = node_ids[i % n_sessions]
                sid = f"b.{nid}"
                b0 = time.perf_counter()
                d.heartbeat(nid, sid)
                lat.append(time.perf_counter() - b0)
            qs = quantiles_nearest_rank(sorted(lat), (50, 99))

            # THE storm: one service-wide update dirties all sessions
            rev += 1

            def touch(tx, rev=rev):
                for i in range(n_sessions):
                    cur = tx.get_task(f"st{i:06d}").copy()
                    cur.annotations.labels = {"rev": str(rev)}
                    tx.update(cur)
            store.update(touch)
            m0 = dict(d.metrics)
            d._mark_dirty_many(node_ids)
            t0 = time.perf_counter()
            d._send_incrementals()
            flush_s = time.perf_counter() - t0
            dm = {k: d.metrics[k] - m0[k]
                  for k in ("flushes", "flush_tx", "dirty_walks",
                            "ships", "wire_copies")}
            delivered = 0
            for nid in node_ids:
                ch = d._sessions[nid].channel
                msg = ch.try_get()
                while msg is not None:
                    if msg.type == "incremental" and msg.changes:
                        delivered += 1
                        break
                    msg = ch.try_get()
            per_shard[str(P)] = {
                "prime_s": round(prime_s, 3),
                "flush_s": round(flush_s, 3),
                "sessions_per_s": round(n_sessions / flush_s)
                if flush_s else None,
                "store_tx_per_flush": round(
                    dm["flush_tx"] / dm["flushes"], 3)
                if dm["flushes"] else None,
                "dirty_walks_per_shard": round(
                    dm["dirty_walks"] / (dm["flushes"] * P), 3)
                if dm["flushes"] else None,
                "copies_per_ship": round(
                    dm["wire_copies"] / dm["ships"], 3)
                if dm["ships"] else None,
                "beat_p50_us": round(qs[50] * 1e6, 1),
                "beat_p99_us": round(qs[99] * 1e6, 1),
                "delivered": delivered,
            }
        finally:
            d.stop()

    # follower read slice: lease-gated read streams off the same store
    # (stub lease — single-process bench; the staleness bound itself is
    # FakeClock-pinned in tests/test_dispatcher_fanout.py)
    class _LeaseStub:
        def read_ok(self):
            return True

    plane = FollowerReadPlane(store, _LeaseStub())
    t0 = time.perf_counter()
    for nid in node_ids[:follower_reads]:
        plane.assignments(nid)
    follower_s = time.perf_counter() - t0
    total_reads = follower_reads + n_sessions * len(shard_counts)

    # ---- ISSUE 16 leg 1: columnar diff gate vs the dict oracle ------
    # Two driven planes on the SAME store: gated P=4 vs a single-plane
    # dict oracle (pre-16 shape: _diffcols=None). A zero-delta soft
    # storm times the gate's vectorized skip against the oracle's full
    # dict walk; a REAL soft storm (service-wide touch) checks sampled
    # wire parity and that the gate dict-diffs exactly the sessions
    # with deltas. Both planes get the reverse-index prime — the gate
    # requires _vol_index_primed (a driven dispatcher never ran _run).
    def _norm(msg, ver=True):
        out = []
        for a in msg.changes:
            ident = a.item if isinstance(a.item, str) else a.item.id
            v = (a.item.meta.version.index
                 if ver and a.action == "update"
                 and not isinstance(a.item, str)
                 and hasattr(a.item, "meta") else None)
            out.append((a.action, a.kind, ident, v))
        return (msg.type, tuple(sorted(out, key=repr)))

    def _inject(d, ids, limit=None):
        grace = d.heartbeat_period * 3
        for nid in ids:
            s = Session(node_id=nid, session_id=f"b.{nid}",
                        channel=Channel(matcher=None, limit=limit))
            d._sessions[nid] = s
            d._hb_wheel.add(nid, grace, lambda: None)

    def _drain(d, ids, sample=None, ver=True):
        delivered = 0
        msgs = {}
        for nid in ids:
            ch = d._sessions[nid].channel
            got = []
            msg = ch.try_get()
            while msg is not None:
                if msg.type == "incremental" and msg.changes:
                    got.append(_norm(msg, ver=ver)
                               if sample is not None and nid in sample
                               else None)
                msg = ch.try_get()
            if got:
                delivered += 1
            if sample is not None and nid in sample:
                msgs[nid] = tuple(got)
        return delivered, msgs

    d_g = Dispatcher(store, heartbeat_period=120.0,
                     rate_limit_period=-1.0, shards=4, jitter_seed=16)
    d_o = Dispatcher(store, heartbeat_period=120.0,
                     rate_limit_period=-1.0, shards=1)
    d_o._diffcols = None               # single-plane dict oracle
    try:
        gate_on = d_g._diffcols is not None
        for d in (d_g, d_o):
            store.view(d._prime_reverse_indexes)
            _inject(d, node_ids)
            d._mark_dirty_many(node_ids)
            d._send_incrementals()
            _drain(d, node_ids)

        # zero-delta soft storm: nothing changed since the prime, every
        # session soft-marked — the gate must prove + skip them ALL
        g0, o0 = dict(d_g.metrics), dict(d_o.metrics)
        for nid in node_ids:
            d_g._mark_dirty(nid, hard=False)
            d_o._mark_dirty(nid, hard=False)
        t0 = time.perf_counter()
        d_g._send_incrementals()
        gate_zero_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        d_o._send_incrementals()
        dict_zero_s = time.perf_counter() - t0
        gz = {k: d_g.metrics[k] - g0[k]
              for k in ("dict_diffs", "zero_delta_skips",
                        "diff_rows_scanned", "ships")}
        zero_ships = gz["ships"] + (d_o.metrics["ships"] - o0["ships"])

        # the REAL soft storm: every task changes, then soft marks —
        # every session has a delta, so the gate must dict-diff and
        # ship the world; sampled wire parity vs the oracle
        rev += 1

        def touch16(tx, rev=rev):
            for i in range(n_sessions):
                cur = tx.get_task(f"st{i:06d}").copy()
                cur.annotations.labels = {"rev": str(rev)}
                tx.update(cur)
        store.update(touch16)
        sample = set(node_ids[::max(1, n_sessions // 1000)])
        g1 = dict(d_g.metrics)
        for nid in node_ids:
            d_g._mark_dirty(nid, hard=False)
            d_o._mark_dirty(nid, hard=False)
        t0 = time.perf_counter()
        d_g._send_incrementals()
        gate_real_s = time.perf_counter() - t0
        d_o._send_incrementals()
        g_del, g_msgs = _drain(d_g, node_ids, sample=sample)
        o_del, o_msgs = _drain(d_o, node_ids, sample=sample)
        gr = {k: d_g.metrics[k] - g1[k] for k in ("dict_diffs", "ships")}
        diff_plane = {
            "gate_enabled": gate_on,
            "zero_delta_flush_s": round(gate_zero_s, 3),
            "dict_oracle_zero_flush_s": round(dict_zero_s, 3),
            "zero_delta_speedup": round(dict_zero_s / gate_zero_s, 2)
            if gate_zero_s else None,
            "zero_delta_skips": gz["zero_delta_skips"],
            "diff_rows_scanned": gz["diff_rows_scanned"],
            "zero_storm_dict_diffs": gz["dict_diffs"],
            "zero_storm_ships": zero_ships,
            "real_storm_flush_s": round(gate_real_s, 3),
            "real_storm_dict_diffs": gr["dict_diffs"],
            "real_storm_ships": gr["ships"],
            "parity_sample": len(sample),
            "wire_parity": (g_msgs == o_msgs and g_del == o_del
                            and g_del == n_sessions
                            and zero_ships == 0),
        }
    finally:
        d_g.stop()
        d_o.stop()

    # ---- ISSUE 16 leg 2: the honest serve-ceiling storm -------------
    # A fresh store at `ceiling_sessions` (seeded + touched in 100k
    # chunks; per-session channels CAPPED at 8 — the 1M OOM was queued
    # wire copies, satellite 2's fix). Planes run SEQUENTIALLY (two
    # resident 1M-session planes would double peak memory), first shard
    # count as the dict oracle, so wire parity across planes is
    # compared with version indexes STRIPPED (each plane serves its own
    # touch rev). Columns record where the GIL binds: the hard serve is
    # the pure-Python dict walk, the gate flush is the numpy skip pass.
    serve_ceiling = {"sessions": ceiling_sessions, "per_shard": {}}
    cstore = MemoryStore()
    CHUNK = 100_000

    def _cseed(lo, hi):
        def seed_chunk(tx):
            for i in range(lo, hi):
                n = Node(id=f"cl{i:07d}")
                n.status.state = NodeStatusState.READY
                tx.create(n)
                t = Task(id=f"clt{i:07d}", service_id="ceilsvc",
                         node_id=n.id, slot=i + 1)
                t.status.state = TaskState.RUNNING
                t.desired_state = TaskState.RUNNING
                tx.create(t)
        return seed_chunk
    for lo in range(0, ceiling_sessions, CHUNK):
        cstore.update(_cseed(lo, min(lo + CHUNK, ceiling_sessions)))
    cids = [f"cl{i:07d}" for i in range(ceiling_sessions)]
    csample = set(cids[::max(1, ceiling_sessions // 1000)])
    cwire = {}
    crev = 0
    oracle_P = ceiling_shards[0]
    for P in ceiling_shards:
        d = Dispatcher(cstore, heartbeat_period=120.0,
                       rate_limit_period=-1.0, shards=P, jitter_seed=16)
        if P == oracle_P:
            d._diffcols = None         # single-plane dict oracle
        try:
            cstore.view(d._prime_reverse_indexes)
            _inject(d, cids, limit=8)  # capped per-session buffers
            d._mark_dirty_many(cids)
            t0 = time.perf_counter()
            d._send_incrementals()
            prime_s = time.perf_counter() - t0
            _drain(d, cids)

            # zero-delta gate flush: all-soft, nothing changed — the
            # gated plane skips the world, the oracle dict-walks it
            z0 = dict(d.metrics)
            for nid in cids:
                d._mark_dirty(nid, hard=False)
            t0 = time.perf_counter()
            d._send_incrementals()
            gate_flush_s = time.perf_counter() - t0
            zd = {k: d.metrics[k] - z0[k]
                  for k in ("dict_diffs", "zero_delta_skips")}

            # the real storm: touch every task (chunked), hard-mark the
            # world, ONE flush serves it — the pure-Python dict walk
            crev += 1
            for lo in range(0, ceiling_sessions, CHUNK):
                hi = min(lo + CHUNK, ceiling_sessions)

                def ctouch(tx, lo=lo, hi=hi, rev=crev):
                    for i in range(lo, hi):
                        cur = tx.get_task(f"clt{i:07d}").copy()
                        cur.annotations.labels = {"rev": str(rev)}
                        tx.update(cur)
                cstore.update(ctouch)
            s0 = dict(d.metrics)
            d._mark_dirty_many(cids)
            t0 = time.perf_counter()
            d._send_incrementals()
            serve_flush_s = time.perf_counter() - t0
            sd = {k: d.metrics[k] - s0[k]
                  for k in ("flushes", "flush_tx", "dirty_walks")}
            delivered, msgs = _drain(d, cids, sample=csample, ver=False)
            cwire[P] = msgs
            serve_ceiling["per_shard"][str(P)] = {
                "dict_oracle": P == oracle_P,
                "prime_s": round(prime_s, 3),
                "gate_flush_s": round(gate_flush_s, 3),
                "zero_delta_skips": zd["zero_delta_skips"],
                "gate_dict_diffs": zd["dict_diffs"],
                "serve_flush_s": round(serve_flush_s, 3),
                "sessions_per_s": round(ceiling_sessions / serve_flush_s)
                if serve_flush_s else None,
                "store_tx_per_flush": round(
                    sd["flush_tx"] / sd["flushes"], 3)
                if sd["flushes"] else None,
                "dirty_walks_per_shard": round(
                    sd["dirty_walks"] / (sd["flushes"] * P), 3)
                if sd["flushes"] else None,
                "delivered": delivered,
            }
        finally:
            d.stop()
    del cstore, cids
    sc0 = serve_ceiling["per_shard"][str(ceiling_shards[0])]
    scN = serve_ceiling["per_shard"][str(ceiling_shards[-1])]
    serve_ceiling["serve_speedup_p1_to_pN"] = round(
        sc0["serve_flush_s"] / scN["serve_flush_s"], 2) \
        if scN["serve_flush_s"] else None
    serve_ceiling["gate_speedup_vs_dict_zero"] = round(
        sc0["gate_flush_s"] / scN["gate_flush_s"], 2) \
        if scN["gate_flush_s"] else None
    serve_ceiling["op_counts_ok"] = all(
        v["store_tx_per_flush"] == 1.0
        and (v["dirty_walks_per_shard"] or 0) <= 1.0
        and v["delivered"] == ceiling_sessions
        for v in serve_ceiling["per_shard"].values())
    serve_ceiling["wire_parity"] = all(
        cwire[P] == cwire[oracle_P] for P in ceiling_shards)
    serve_ceiling["gil_note"] = (
        "the hard-serve dict walk is pure Python (one GIL for the shard"
        " pool), so serve speedup flattens near 1.0 as P grows; the"
        " columnar gate wins by SKIPPING zero-delta sessions in a numpy"
        " pass, not by parallelizing the walk")

    ok = all(v["delivered"] == n_sessions
             and v["store_tx_per_flush"] == 1.0
             and (v["dirty_walks_per_shard"] or 0) <= 1.0
             for v in per_shard.values())
    base = per_shard.get(str(shard_counts[0]), {}).get("flush_s")
    return {
        "sessions": n_sessions,
        "shards": per_shard,
        "scale_p1_to_p4": round(base / per_shard["4"]["flush_s"], 2)
        if base and "4" in per_shard and per_shard["4"]["flush_s"]
        else None,
        "follower_reads": follower_reads,
        "follower_read_s": round(follower_s, 3),
        "follower_read_ratio": round(
            plane.metrics["reads_served"] / total_reads, 4)
        if total_reads else None,
        "diff_plane": diff_plane,
        "serve_ceiling": serve_ceiling,
        "parity": (ok and plane.metrics["reads_served"] == follower_reads
                   and diff_plane["wire_parity"]
                   and serve_ceiling["wire_parity"]
                   and serve_ceiling["op_counts_ok"]),
    }


def bench_mesh_cluster_step(np, n_nodes=None, total_tasks=1_000_000):
    """ISSUE 7: the fused flagship (placement fill + raft quorum tally +
    commit-frontier advance in ONE jit) sharded over the `nodes` mesh
    axis at the scale-out grid — ≥131072 nodes × 1M tasks, the shape the
    Go reference cannot hold in one scheduler pass. Columns: devices,
    per-shard node count, H2D bytes (chunked shard uploads), fill vs e2e
    split. Parity at this size rides the sampled-shard oracle +
    invariant ladder (parallel/shard_parity.py; full-oracle parity for
    the same kernel is pinned at feasible shapes by the grid rows and
    tests) — a regression flips parity=False, joins failed_rows, and the
    bench exits nonzero."""
    import jax
    from swarmkit_tpu.models.cluster_step import synth_shard_cluster
    from swarmkit_tpu.ops.raft_replay import replay_commit
    from swarmkit_tpu.parallel.mesh import make_mesh, sharded_cluster_step
    from swarmkit_tpu.parallel.shard_parity import (
        check_fill_invariants,
        sampled_shard_parity,
    )

    n_dev = 1 << (max(len(jax.devices()), 1).bit_length() - 1)
    mesh = make_mesh(n_dev)
    if n_nodes is None:
        n_nodes = max(131_072, 16_384 * n_dev)
    gps = 2                                   # groups per shard
    tpg = -(-total_tasks // (gps * n_dev))
    t0 = time.perf_counter()
    p, gshard = synth_shard_cluster(n_nodes, n_dev, groups_per_shard=gps,
                                    tasks_per_group=tpg, lmax=2)
    synth_s = time.perf_counter() - t0
    managers, log_len = 5, 1 << 15
    acks = np.zeros((managers, log_len), bool)
    frontier = np.random.RandomState(2).randint(
        log_len // 2, log_len, managers)
    for m in range(managers):
        acks[m, :frontier[m]] = True
    quorum = managers // 2 + 1
    stats = {}
    t0 = time.perf_counter()
    counts, commit = sharded_cluster_step(p, acks, np.int32(quorum), mesh,
                                          stats=stats)
    e2e_s = time.perf_counter() - t0
    parity = True
    inv, shards = {}, []
    try:
        assert commit == int(replay_commit(acks, quorum)[0]), \
            "fused commit frontier != replay_commit"
        inv = check_fill_invariants(p, counts)
        shards = sampled_shard_parity(p, counts, gshard, n_dev,
                                      min(2, n_dev))
    except AssertionError as exc:
        parity = False
        inv = {"violation": str(exc).splitlines()[0]}
    return {
        "parity": parity,
        "devices": n_dev,
        "nodes": n_nodes,
        "per_shard_nodes": n_nodes // n_dev,
        "tasks": int(p.n_tasks.sum()),
        "placed": inv.get("placed"),
        "h2d_bytes": stats.get("h2d_bytes"),
        "h2d_mb": round(stats.get("h2d_bytes", 0) / 1e6, 1),
        "d2h_bytes": stats.get("d2h_bytes"),
        "upload_s": round(stats.get("upload_s", 0.0), 3),
        "fill_s": round(stats.get("fill_s", 0.0), 3),
        "pull_s": round(stats.get("pull_s", 0.0), 4),
        "e2e_s": round(e2e_s, 3),
        "synth_s": round(synth_s, 3),
        "sampled_shards": shards,
        "commit_index": int(commit),
    }


def bench_strategy_grid(np, n_nodes=2_000, n_tasks=20_000, n_services=50,
                        scaleout_nodes=None, scaleout_tasks=262_144,
                        steady_waves=3):
    """ISSUE 19: strategy diversity — spread vs binpack vs topology-aware
    scoring through the SAME water-fill kernel, parity gated at two
    shapes. Steady-tick: a fresh tracked encoder + resident state per
    strategy, cold tick then steady waves via the classic tick
    decomposition, kernel vs CPU-oracle bit-parity every wave
    (binpack rides the heap/closed-form oracle pair, topology the
    prepended outermost spread level). Scale-out: the shard-partitioned
    synth grid per strategy — oracle-infeasible sizing rides the
    sampled-shard oracle + the invariant ladder, including the
    topology-balance water check (parallel/shard_parity.py). The
    scale-out shape here is a mid-size grid (16k × devices nodes) — the
    131k flagship shape stays owned by mesh_cluster_step; this row
    measures STRATEGY deltas, not the ceiling."""
    import jax
    from swarmkit_tpu.models.cluster_step import synth_shard_cluster
    from swarmkit_tpu.ops.resident import ResidentPlacement
    from swarmkit_tpu.parallel.mesh import make_mesh, sharded_schedule
    from swarmkit_tpu.parallel.shard_parity import (
        check_fill_invariants,
        sampled_shard_parity,
    )
    from swarmkit_tpu.scheduler import batch
    from swarmkit_tpu.scheduler.encode import IncrementalEncoder

    n_dev = 1 << (max(len(jax.devices()), 1).bit_length() - 1)
    mesh = make_mesh(n_dev)
    if scaleout_nodes is None:
        scaleout_nodes = 16_384 * n_dev
    gps = 2
    tpg = -(-scaleout_tasks // (gps * n_dev))

    parity = True
    strategies = {}
    for strat in ("spread", "binpack", "topology"):
        rng = random.Random(19)
        infos = _mk_nodes(rng, n_nodes)
        topo = "node.labels.zone" if strat == "topology" else None
        enc = IncrementalEncoder(tracked=True, strategy=strat, topology=topo)
        rp = ResidentPlacement(enc)
        cold = _tick(enc, rp, infos,
                     _mk_groups(rng, n_tasks, n_services, wave=0), batch, np)
        parity &= cold["parity"]
        _apply_wave(enc, rp, infos, cold["problem"], cold["counts"], batch)
        steady = []
        for w in range(steady_waves):
            r = _tick(enc, rp, infos,
                      _mk_groups(rng, n_tasks, n_services, wave=1 + w),
                      batch, np)
            parity &= r["parity"]
            _apply_wave(enc, rp, infos, r["problem"], r["counts"], batch)
            steady.append(r)
        best = min(steady, key=lambda r: r["tpu_tick_s"])

        p, gshard = synth_shard_cluster(scaleout_nodes, n_dev,
                                        groups_per_shard=gps,
                                        tasks_per_group=tpg, lmax=2,
                                        strategy=strat)
        t0 = time.perf_counter()
        counts = sharded_schedule(p, mesh)
        scaleout_s = time.perf_counter() - t0
        inv = {}
        try:
            inv = check_fill_invariants(p, counts)
            sampled_shard_parity(p, counts, gshard, n_dev, 1)
        except AssertionError as exc:
            parity = False
            inv = {"violation": str(exc).splitlines()[0]}
        strategies[strat] = {
            "steady_tick_s": round(best["tpu_tick_s"], 4),
            "steady_device_s": round(best["device_s"], 4),
            "steady_cpu_tick_s": round(best["cpu_tick_s"], 4),
            "steady_placed": best["placed"],
            "scaleout_e2e_s": round(scaleout_s, 3),
            "scaleout_placed": inv.get("placed"),
            **({"violation": inv["violation"]} if "violation" in inv else {}),
        }
    return {
        "parity": parity,
        "devices": n_dev,
        "nodes": n_nodes,
        "tasks": n_tasks,
        "scaleout_nodes": scaleout_nodes,
        "scaleout_tasks": scaleout_tasks,
        "strategies": strategies,
    }


def bench_trace_plane(np):
    """Trace-plane acceptance row (ISSUE 5): (a) DISARMED overhead — a
    pipelined steady wave with tracing off must allocate zero spans
    (the failpoints-style truthiness contract) and cost the same wall as
    before the plane existed; (b) ARMED, the same waves yield the
    per-stage breakdown column (mean seconds per span name from the
    flight recorder) plus the measured armed-vs-disarmed overhead.

    Shapes are deliberately small: this row measures the INSTRUMENTATION,
    not the kernel — the grid rows above own the kernel numbers."""
    import gc

    from swarmkit_tpu.ops.pipeline import TickPipeline
    from swarmkit_tpu.ops.resident import ResidentPlacement
    from swarmkit_tpu.scheduler import batch
    from swarmkit_tpu.scheduler.encode import IncrementalEncoder
    from swarmkit_tpu.utils import trace

    N_NODES_T, N_TASKS_T, N_SVCS_T, WAVES, DEPTH = 512, 2_000, 10, 8, 2

    def run_waves(tag):
        # fresh encoder + node state per run: the three runs (warm /
        # disarmed / armed) must do identical work, not accumulate tasks
        rng = random.Random(11)
        infos = _mk_nodes(rng, N_NODES_T)
        by_node = {i.node.id: i for i in infos}

        def commit(p, counts):
            orders = batch.materialize_orders(p, counts)
            infos_arr = [by_node[nid] for nid in p.node_ids]
            batch.apply_wave(infos_arr, p.groups, orders)

        enc = IncrementalEncoder()
        rp = ResidentPlacement(enc)
        pipe = TickPipeline(enc, rp, commit, depth=DEPTH,
                            async_commit=True)
        waves = [_mk_groups(rng, N_TASKS_T, N_SVCS_T, wave=w)
                 for w in range(WAVES)]
        try:
            for w in range(WAVES):
                gc.collect()
                pipe.tick(infos, waves[w])
            pipe.flush()
        finally:
            pipe.close()
        # steady TICK rows only: tick() records one timing per call
        # (indices 0..WAVES-1, fill-in ticks included); flush() appends
        # its per-drained-wave rows strictly AFTER, so [DEPTH+1:WAVES]
        # can never pick a cheap drain-path wall
        assert len(pipe.timings) == WAVES + DEPTH
        return min(t["wall_s"] for t in pipe.timings[DEPTH + 1:WAVES])

    run_waves("warm")                      # compile + device warm-up

    # (a) disarmed: the op-count guard — any Span construction or record
    # filing on the hot path trips the probe
    allocs = {"n": 0}
    orig_init, orig_record = trace.Span.__init__, \
        trace.FlightRecorder.record

    def spy_init(self, *a, **k):
        allocs["n"] += 1
        orig_init(self, *a, **k)

    def spy_record(self, *a, **k):
        allocs["n"] += 1
        orig_record(self, *a, **k)

    trace.Span.__init__ = spy_init
    trace.FlightRecorder.record = spy_record
    try:
        disarmed_wave_s = run_waves("off")
        disarmed_allocs = allocs["n"]
    finally:
        trace.Span.__init__ = orig_init
        trace.FlightRecorder.record = orig_record

    # (b) armed: same shape, recorder on → per-stage breakdown
    rec = trace.arm(capacity=16384)
    try:
        armed_wave_s = run_waves("on")
        by_stage: dict[str, list[float]] = {}
        for r in rec.snapshot():
            by_stage.setdefault(r["name"], []).append(r["dur"])
    finally:
        trace.disarm()
    breakdown = {
        name: {"n": len(ds),
               "mean_ms": round(sum(ds) / len(ds) * 1e3, 4),
               "total_s": round(sum(ds), 4)}
        for name, ds in sorted(by_stage.items())}

    return {
        "disarmed_wave_s": round(disarmed_wave_s, 5),
        "armed_wave_s": round(armed_wave_s, 5),
        "armed_overhead_x": round(armed_wave_s / disarmed_wave_s, 3),
        # THE acceptance: tracing off allocates nothing on the hot path
        "disarmed_span_allocs": disarmed_allocs,
        "stage_breakdown": breakdown,
        "spans_recorded": rec.spans_started,
        "parity": disarmed_allocs == 0 and bool(breakdown),
    }


def bench_lint_plane(np):
    """Analysis-plane acceptance row (ISSUE 8), the trace_plane shape:
    (a) DISARMED, the lockgraph factory hands out the PLAIN threading
    primitive — acquire stays native C and constructing/acquiring
    allocates zero tracker objects (the failpoints/trace truthiness
    contract, spied the same way trace_plane spies Span.__init__);
    (b) ARMED, the tracked wrapper's acquire overhead is measured
    (armed-vs-disarmed ratio — per-test cost, never production);
    (c) the full AST rule set + the mirrored-tick drift check run over
    the tree and must come back clean (what tier-1's
    tests/test_lint_clean.py gates, timed here)."""
    import threading
    import time as _time
    from pathlib import Path

    from swarmkit_tpu.analysis import lint, lockgraph, mirror

    N, BATCHES = 20_000, 5

    def acquire_wall(lock) -> float:
        """min-of-batches seconds for N acquire/release pairs (the
        host-micro discipline: sub-10ms timings are jitter-bound)."""
        best = float("inf")
        for _ in range(BATCHES):
            t0 = _time.perf_counter()
            for _ in range(N):
                with lock:
                    pass
            best = min(best, _time.perf_counter() - t0)
        return best

    # (a) disarmed: the op-count guard — any _TrackedLock construction
    # or graph record while disarmed trips the probe
    allocs = {"n": 0}
    orig_init = lockgraph._TrackedLock.__init__

    def spy_init(self, *a, **k):
        allocs["n"] += 1
        orig_init(self, *a, **k)

    lockgraph._TrackedLock.__init__ = spy_init
    try:
        lockgraph.disarm()
        plain = lockgraph.make_lock("bench.lint_plane")
        plain_is_native = type(plain) is type(threading.Lock())
        disarmed_s = acquire_wall(plain)
        # ISSUE 12 raw-condition routing: a Condition over the factory
        # primitive must also stay native-backed and alloc-free disarmed
        cond = threading.Condition(
            lockgraph.make_rlock("bench.lint_plane.cond"))
        cond_is_native = type(cond._lock) is type(threading.RLock())
        disarmed_allocs = allocs["n"]
    finally:
        lockgraph._TrackedLock.__init__ = orig_init

    # (b) armed: tracked wrapper overhead + a clean report
    state = lockgraph.arm()
    try:
        tracked = lockgraph.make_lock("bench.lint_plane")
        armed_s = acquire_wall(tracked)
        graph_clean = state.report().clean
    finally:
        lockgraph.disarm()

    # (c) the static passes over the tree (repo root = bench.py's dir)
    root = Path(__file__).resolve().parent
    t0 = _time.perf_counter()
    findings = lint.lint_tree(root)
    drift = mirror.check_drift(root)
    static_s = _time.perf_counter() - t0

    return {
        "disarmed_acquire_ns": round(disarmed_s / N * 1e9, 1),
        "armed_acquire_ns": round(armed_s / N * 1e9, 1),
        "armed_overhead_x": round(armed_s / max(disarmed_s, 1e-12), 2),
        # THE acceptance: disarmed hands out the native primitive and
        # allocates nothing
        "disarmed_tracked_allocs": disarmed_allocs,
        "disarmed_is_native_lock": plain_is_native,
        "disarmed_condition_is_native": cond_is_native,
        "lint_findings": len(findings),
        "mirror_drift_clean": drift.clean,
        # full pass now includes the ISSUE 12 dataflow rules (CFG +
        # taint over the whole tree) and every registered mirror pair;
        # tier-1 pins the same pass under a 10 s wall budget
        "static_pass_s": round(static_s, 3),
        "static_pass_budget_ok": static_s <= 10.0,
        "parity": (disarmed_allocs == 0 and plain_is_native
                   and cond_is_native and graph_clean
                   and not findings and drift.clean
                   and static_s <= 10.0),
    }


def bench_slo_plane(np):
    """Lifecycle-plane acceptance row (ISSUE 10), the trace_plane shape:
    (a) DISARMED, an end-to-end task slice — orchestrator task factory,
    scheduler serial wave commit, dispatcher ship + status flush — files
    ZERO timeline records (the truthiness contract, spied the way
    trace_plane spies Span.__init__); (b) ARMED, the same slice produces
    complete NEW→ASSIGNED→SHIPPED→RUNNING timelines, the scheduler's
    record site is ONE batched call for the whole wave (never per placed
    task), and the armed-vs-disarmed overhead is measured."""
    from swarmkit_tpu.api.objects import Node, Service, TaskStatus
    from swarmkit_tpu.api.specs import NodeDescription, Resources
    from swarmkit_tpu.api.types import NodeStatusState, TaskState
    from swarmkit_tpu.dispatcher.dispatcher import Dispatcher
    from swarmkit_tpu.orchestrator.task import new_task
    from swarmkit_tpu.scheduler.scheduler import Scheduler
    from swarmkit_tpu.store.memory import MemoryStore
    from swarmkit_tpu.utils import lifecycle, slo

    N_NODES_S, N_TASKS_S = 64, 1_000

    def run_slice():
        """One store: PENDING tasks -> scheduler wave -> dispatcher ship
        -> agent-style RUNNING write-back. Returns (wave_s, flush_s)."""
        store = MemoryStore()
        svc = Service(id="slosvc")
        svc.spec.annotations.name = "slosvc"

        def seed(tx):
            tx.create(svc)
            for i in range(N_NODES_S):
                n = Node(id=f"sn{i:04d}")
                n.status.state = NodeStatusState.READY
                n.description = NodeDescription(
                    hostname=n.id,
                    resources=Resources(nano_cpus=64 * 10**9,
                                        memory_bytes=256 * 2**30))
                tx.create(n)
            for i in range(N_TASKS_S):
                t = new_task(None, svc, i + 1)     # NEW record site
                t.status.state = TaskState.PENDING  # allocator shortcut
                tx.create(t)
        store.update(seed)

        sched = Scheduler(store, backend="cpu")
        sched_ch = sched._setup()
        r = lifecycle.recorder()
        b0 = r.batches if r is not None else 0
        t0 = time.perf_counter()
        sched.tick()                               # one batched ASSIGNED
        wave_s = time.perf_counter() - t0
        wave_batches = (r.batches - b0) if r is not None else 0
        store.queue.stop_watch(sched_ch)

        d = Dispatcher(store, heartbeat_period=300.0)
        _, ch = store.view_and_watch(d._prime_reverse_indexes,
                                     matcher=lambda ev: True, limit=None)
        try:
            sid = d.register("sn0000")
            d.assignments("sn0000", sid)           # SHIPPED record site
            assigned = store.view(
                lambda tx: [t.id for t in tx.find_tasks()
                            if t.node_id == "sn0000"])
            d.update_task_status(
                "sn0000", sid,
                [(tid, TaskStatus(state=TaskState.RUNNING))
                 for tid in assigned])
            t0 = time.perf_counter()
            d._flush_statuses()                    # RUNNING record site
            flush_s = time.perf_counter() - t0
        finally:
            store.queue.stop_watch(ch)
            d._hb_wheel.stop()
        return wave_s, flush_s, wave_batches

    run_slice()                                    # warm-up

    # (a) disarmed: the op-count guard — ANY recorder method running on
    # the slice trips the probe (module sites must bail on the
    # truthiness test before reaching the recorder)
    allocs = {"n": 0}
    orig = {name: getattr(lifecycle.LifecycleRecorder, name)
            for name in ("record", "record_batch", "record_pairs")}

    def spy(name):
        def wrapper(self, *a, **k):
            allocs["n"] += 1
            return orig[name](self, *a, **k)
        return wrapper

    for name in orig:
        setattr(lifecycle.LifecycleRecorder, name, spy(name))
    try:
        disarmed_wave_s, disarmed_flush_s, _ = run_slice()
        disarmed_allocs = allocs["n"]

        # (b) armed: full timelines + the one-batched-call-per-wave pin
        with lifecycle.armed() as rec:
            armed_wave_s, armed_flush_s, sched_batches = run_slice()
            samples = rec.startup_samples()
            transitions = {f"{a}->{b}": n for (a, b), n
                           in sorted(rec.transition_counts().items())}
            attribution = slo.attribution(rec)
    finally:
        for name, fn in orig.items():
            setattr(lifecycle.LifecycleRecorder, name, fn)

    return {
        "nodes": N_NODES_S, "tasks": N_TASKS_S,
        "disarmed_wave_s": round(disarmed_wave_s, 5),
        "armed_wave_s": round(armed_wave_s, 5),
        "disarmed_flush_s": round(disarmed_flush_s, 5),
        "armed_flush_s": round(armed_flush_s, 5),
        "armed_overhead_x": round(
            armed_wave_s / max(disarmed_wave_s, 1e-9), 3),
        # THE acceptance: the plane off allocates nothing anywhere on
        # the slice, and armed the wave files ONE batched record
        "disarmed_record_allocs": disarmed_allocs,
        "sched_record_batches_per_wave": sched_batches,
        "startup_samples": len(samples),
        "startup_p99_s": slo.quantile_nearest_rank(samples, 99),
        "transitions": transitions,
        "attribution_reconciled": attribution["reconciled"],
        "parity": (disarmed_allocs == 0 and sched_batches == 1
                   and len(samples) > 0 and attribution["reconciled"]),
    }


def bench_telemetry_plane(np, n_nodes=10_000, beat_nodes=256,
                          beats_per_node=4):
    """Telemetry-plane acceptance row (ISSUE 15), the slo_plane shape:
    (a) DISARMED, a driven beat storm over `beat_nodes` sessions builds
    ZERO snapshots and stores ZERO reports (spies on
    metrics.registry_snapshot and Dispatcher._record_report — the
    truthiness contract); (b) ARMED, the piggyback overhead per beat
    (build + shard store) is measured against the bare beat; (c) the
    rollup MERGE throughput over `n_nodes` synthetic per-node
    snapshots; (d) the driven parity gate — merged cluster counters
    equal the manual sum, and a silent node goes stale (FakeClock)."""
    from functools import reduce

    from swarmkit_tpu.dispatcher.dispatcher import Dispatcher
    from swarmkit_tpu.manager.telemetry import TelemetryAggregator
    from swarmkit_tpu.store.memory import MemoryStore
    from swarmkit_tpu.utils import metrics, telemetry
    from swarmkit_tpu.utils.clock import FakeClock
    from swarmkit_tpu.utils.metrics import (
        CounterFamily,
        Histogram,
        empty_snapshot,
        merge_snapshot,
        registry_snapshot,
        snapshot_counter_value,
    )

    def node_snap(i):
        cf = CounterFamily("swarm_rpc_handled_total", "h", ("method",))
        cf.inc(("tick",), i + 1)
        cf.inc(("status",), 2 * i + 1)
        h = Histogram("swarm_store_tx_seconds", "h")
        h.observe(0.001 * ((i % 7) + 1))
        return registry_snapshot(families=[cf], histograms=[h],
                                 gauges={"agent_tasks": i % 5})

    store = MemoryStore()
    d = Dispatcher(store, heartbeat_period=300.0, shards=4)
    sids = {}
    for i in range(beat_nodes):
        nid = f"tn{i:05d}"
        sids[nid] = d.register(nid)

    # (a) disarmed beat storm: spy every surface that could build/store
    builds = {"n": 0}
    stores = {"n": 0}
    orig_snap = metrics.registry_snapshot
    orig_rec = Dispatcher._record_report

    def spy_snap(*a, **k):
        builds["n"] += 1
        return orig_snap(*a, **k)

    def spy_rec(self, *a, **k):
        stores["n"] += 1
        return orig_rec(self, *a, **k)

    try:
        metrics.registry_snapshot = spy_snap
        Dispatcher._record_report = spy_rec
        t0 = time.perf_counter()
        for _ in range(beats_per_node):
            for nid, sid in sids.items():
                # the agent-loop shape: guard first, bare beat when off
                if telemetry.enabled():
                    d.heartbeat(nid, sid,
                                metrics=telemetry.node_snapshot())
                else:
                    d.heartbeat(nid, sid)
        disarmed_s = time.perf_counter() - t0
        disarmed_builds = builds["n"]
        disarmed_stores = stores["n"]
        n_beats = beats_per_node * len(sids)

        # (b) armed: every beat piggybacks (report_every=1 — the bench
        # measures the per-piggyback ceiling, not the amortized cadence)
        with telemetry.armed(report_every=1):
            t0 = time.perf_counter()
            for _ in range(beats_per_node):
                for nid, sid in sids.items():
                    if telemetry.enabled():
                        d.heartbeat(nid, sid,
                                    metrics=telemetry.node_snapshot())
                    else:
                        d.heartbeat(nid, sid)
            armed_s = time.perf_counter() - t0
            stored = sum(len(r) for r in d.telemetry_reports())
    finally:
        metrics.registry_snapshot = orig_snap
        Dispatcher._record_report = orig_rec
        d._hb_wheel.stop()

    # (c) rollup merge throughput at n_nodes
    snaps = [node_snap(i) for i in range(n_nodes)]
    t0 = time.perf_counter()
    merged = reduce(merge_snapshot, snaps, empty_snapshot())
    merge_s = time.perf_counter() - t0
    merged_ok = (
        snapshot_counter_value(merged, "swarm_rpc_handled_total",
                               ("tick",))
        == sum(i + 1 for i in range(n_nodes)))

    # (d) driven parity + staleness gate under FakeClock
    clock = FakeClock()
    d2 = Dispatcher(MemoryStore(), heartbeat_period=5.0, clock=clock,
                    shards=4)
    try:
        with telemetry.armed():
            parts = {}
            s2 = {}
            for i in range(8):
                nid = f"pn{i}"
                s2[nid] = d2.register(nid)
                parts[nid] = node_snap(i)
                d2.heartbeat(nid, s2[nid], metrics=parts[nid])
            agg = TelemetryAggregator(MemoryStore(), d2, clock=clock)
            roll = agg.rollup(include_local=False)
            want = reduce(merge_snapshot, parts.values(),
                          empty_snapshot())
            parity_counters = roll["cluster"]["counters"] \
                == want["counters"]
            # pn0 goes silent; the rest re-beat inside the grace
            # window, then time passes the 3x-period staleness bound
            clock.advance(10.0)
            for nid in list(parts)[1:]:
                d2.heartbeat(nid, s2[nid], metrics=parts[nid])
            clock.advance(5.5)
            roll2 = agg.rollup(include_local=False)
            stale_ok = roll2["nodes"]["stale"] == ["pn0"] \
                and roll2["nodes"]["fresh"] == 7
    finally:
        d2._hb_wheel.stop()

    return {
        "beat_nodes": beat_nodes,
        "beats": n_beats,
        # THE acceptance: the plane off builds/stores nothing on the
        # beat path
        "disarmed_beat_allocs": disarmed_builds + disarmed_stores,
        "disarmed_beat_us": round(disarmed_s / n_beats * 1e6, 2),
        "armed_beat_us": round(armed_s / n_beats * 1e6, 2),
        "piggyback_overhead_us": round(
            (armed_s - disarmed_s) / n_beats * 1e6, 2),
        "reports_stored": stored,
        "merge_nodes": n_nodes,
        "merge_s": round(merge_s, 4),
        "merge_nodes_per_s": round(n_nodes / max(merge_s, 1e-9), 1),
        "rollup_counter_exact": merged_ok,
        "driven_parity": parity_counters,
        "stale_detection": stale_ok,
        "parity": (disarmed_builds + disarmed_stores == 0
                   and stored == beat_nodes and merged_ok
                   and parity_counters and stale_ok),
    }


def bench_store_plane(np, sizes=(100_000, 1_000_000)):
    """Columnar store plane acceptance row (ISSUE 11): whole-wave task
    write-back through the object path (per-task get + two tree copies +
    full re-index) vs the columnar plane (`store.assign_wave`) at each
    size — the 1M row is the BENCH_r05 e2e ceiling this plane attacks.
    Reported per size: ops/s for the object path, the eager columnar
    path (the production Scheduler's, events included) and the lazy
    columnar path (array scatter + owed object views; `heal_s` is the
    deferred materialization paid on first object read). Parity is
    end-state equality (state/node/version per task) between paths PLUS
    columns bit-equal to a from-scratch rebuild. Acceptance: lazy
    columnar write-back >= 10x object ops/s (tier-1 smoke-checks the
    same fn at a CPU-smoke size — tests/test_bench_diag.py)."""
    from swarmkit_tpu.api.objects import Node, Task
    from swarmkit_tpu.api.types import NodeStatusState, TaskState
    from swarmkit_tpu.store.columnar import ColumnarTasks
    from swarmkit_tpu.store.memory import MemoryStore

    N_NODES = 64

    def seed_store(n):
        store = MemoryStore()

        def seed_nodes(tx):
            for i in range(N_NODES):
                node = Node(id=f"sp{i:03d}")
                node.status.state = NodeStatusState.READY
                tx.create(node)
        store.update(seed_nodes)

        def seed_tasks(tx):
            for i in range(n):
                t = Task(id=f"t{i:07d}", service_id=f"svc{i % 100}",
                         slot=i + 1)
                t.status.state = TaskState.PENDING
                t.desired_state = TaskState.RUNNING
                tx.create(t)
        store.update(seed_tasks)
        return store

    def image(store):
        return {t.id: (int(t.status.state), t.node_id,
                       t.meta.version.index)
                for t in store.view(lambda tx: tx.find_tasks())}

    out_sizes = {}
    parity_all = True
    for n in sizes:
        wave = [(f"t{i:07d}", f"sp{i % N_NODES:03d}") for i in range(n)]

        # -- object path: the pre-ISSUE-11 write-back shape ------------
        s1 = seed_store(n)

        def write_all(tx):
            for tid, nid in wave:
                cur = tx.get_task(tid).copy()
                cur.node_id = nid
                cur.status.state = TaskState.ASSIGNED
                cur.status.message = "scheduler assigned task to node"
                cur.status.timestamp = time.time()
                tx.update(cur)
        t0 = time.perf_counter()
        s1.update(write_all)
        object_s = time.perf_counter() - t0
        img_obj = image(s1)
        del s1

        # -- eager columnar (the Scheduler's path; events identical) ---
        s2 = seed_store(n)
        t0 = time.perf_counter()
        codes_e, _ = s2.assign_wave(wave)
        eager_s = time.perf_counter() - t0
        ok = all(c == 0 for c in codes_e)
        parity = ok and image(s2) == img_obj
        del s2

        # -- lazy columnar (array scatter; object views owed) ----------
        s3 = seed_store(n)
        t0 = time.perf_counter()
        codes_l, _ = s3.assign_wave(wave, lazy=True)
        lazy_s = time.perf_counter() - t0
        ok = ok and all(c == 0 for c in codes_l)
        t0 = time.perf_counter()
        s3._heal_stale_tasks()
        heal_s = time.perf_counter() - t0
        parity = parity and ok and image(s3) == img_obj
        rebuilt = ColumnarTasks.rebuild(
            s3.view(lambda tx: tx.find_tasks()))
        parity = parity and ColumnarTasks.snapshots_equal(
            s3.columnar.snapshot(), rebuilt.snapshot())
        op_counts = {k: v for k, v in s3.op_counts.items()
                     if k.startswith("columnar")}
        del s3, rebuilt

        parity_all = parity_all and parity
        out_sizes[str(n)] = {
            "object_ops_s": round(n / max(object_s, 1e-9), 1),
            "columnar_eager_ops_s": round(n / max(eager_s, 1e-9), 1),
            "columnar_ops_s": round(n / max(lazy_s, 1e-9), 1),
            "heal_s": round(heal_s, 4),
            "speedup_x": round(object_s / max(lazy_s, 1e-9), 2),
            "speedup_eager_x": round(object_s / max(eager_s, 1e-9), 2),
            "speedup_with_heal_x": round(
                object_s / max(lazy_s + heal_s, 1e-9), 2),
            "op_counts": op_counts,
            "parity": parity,
        }
    return {
        "sizes": out_sizes,
        "speedup_min_x": min(v["speedup_x"] for v in out_sizes.values()),
        "parity": parity_all,
    }


def bench_orchestrator_storm(np, n_services=100_000, replicas=2,
                             dirty=200, storm_services=300,
                             storm_replicas=5, storm_budget_s=180.0):
    """Batched orchestration plane acceptance row (ISSUE 14): (a) the
    columnar reconcile pass over n_services replicated services —
    steady-pass wall vs a scalar decide loop (sampled + extrapolated),
    with decision parity on a seeded dirty subset and the objectless
    op-count contract (zero object reads / zero transactions for steady
    services); (b) a live rolling-update storm (mass v2 push, ~25%
    poisoned services auto-rolling back) through the real orchestrator
    + shared wave planner, reporting time-to-converged and the planner
    thread count (ONE, vs one-per-service scalar updaters); (c) the
    disarmed-plane contract — with SWARMKIT_TPU_NO_BATCHED_ORCH=1 the
    plane's module counters stay untouched by event handling (zero
    per-event allocations on the steady path).

    tests/test_bench_diag.py runs this same fn at a CPU-smoke shape
    (op counts + parity, never wall clock on the 1-core test host)."""
    import random
    import threading

    from swarmkit_tpu.api.objects import Service, Task, Version
    from swarmkit_tpu.api.specs import (Annotations, ContainerSpec,
                                        RestartPolicy, ServiceSpec,
                                        TaskSpec, UpdateConfig)
    from swarmkit_tpu.api.types import (TaskState, UpdateFailureAction,
                                        UpdateOrder)
    from swarmkit_tpu.orchestrator import batched as batched_mod
    from swarmkit_tpu.orchestrator.batched import BatchedReconciler
    from swarmkit_tpu.orchestrator.replicated import (
        ReplicatedOrchestrator, decide_service)
    from swarmkit_tpu.store import by
    from swarmkit_tpu.store.memory import MemoryStore

    rng = random.Random(0)

    def mk_service(sid, n_rep, image="v1", version=1, rollback=True):
        svc = Service(id=sid)
        svc.spec = ServiceSpec(
            annotations=Annotations(name=sid), replicas=n_rep,
            task=TaskSpec(runtime=ContainerSpec(image=image),
                          restart=RestartPolicy(delay=0.05)),
            update=UpdateConfig(
                parallelism=2, delay=0.0, monitor=0.3,
                order=UpdateOrder.STOP_FIRST,
                failure_action=(UpdateFailureAction.ROLLBACK if rollback
                                else UpdateFailureAction.PAUSE),
                max_failure_ratio=0.0))
        svc.spec_version = Version(version)
        return svc

    # ---------------- (a) reconcile pass at n_services ----------------
    store = MemoryStore()

    def seed(batch):
        for s in range(n_services):
            def one(tx, s=s):
                svc = mk_service(f"os{s:06d}", replicas)
                tx.create(svc)
                for slot in range(1, replicas + 1):
                    t = Task(id=f"ot{s:06d}-{slot}", service_id=svc.id,
                             slot=slot)
                    t.spec = svc.spec.task
                    t.spec_version = Version(1)
                    t.desired_state = TaskState.RUNNING
                    t.status.state = TaskState.RUNNING
                    t.node_id = f"n{(s + slot) % 64:03d}"
                    tx.create(t)
            batch.update(one)

    store.batch(seed)
    ids = [f"os{s:06d}" for s in range(n_services)]
    br = BatchedReconciler(store)

    br.decide_many(ids[:8])          # warmup: kernel-module import cost
    br.stats.clear()
    t0 = time.perf_counter()
    steady = br.decide_many(ids)
    steady_pass_s = time.perf_counter() - t0
    steady_ok = (steady == {}
                 and br.stats["services_steady"] == n_services
                 and br.stats["object_reads"] == 0)

    # scalar estimate from a sample (the full scalar loop at 100k is
    # exactly the cost this plane deletes)
    sample = ids[:min(len(ids), 3_000)]
    view = store.view()
    t0 = time.perf_counter()
    for sid in sample:
        svc = view.get_service(sid)
        tasks = [t for t in view.find_tasks(by.ByServiceID(sid))
                 if t.desired_state <= TaskState.RUNNING]
        decide_service(svc, tasks)
    scalar_sample_s = time.perf_counter() - t0
    scalar_est_s = scalar_sample_s * (len(ids) / max(len(sample), 1))

    # dirty a seeded subset; decisions must match the scalar oracle
    dirty_ids = sorted(rng.sample(ids, min(dirty, len(ids))))

    def poke(tx):
        for sid in dirty_ids:
            cur = tx.get_service(sid).copy()
            cur.spec.replicas = replicas + 1      # scale-up decision
            tx.update(cur)

    store.update(poke)
    t0 = time.perf_counter()
    decisions = br.decide_many(ids)
    dirty_pass_s = time.perf_counter() - t0
    view = store.view()
    parity = set(decisions) == set(dirty_ids)
    for sid in dirty_ids:
        svc = view.get_service(sid)
        tasks = [t for t in view.find_tasks(by.ByServiceID(sid))
                 if t.desired_state <= TaskState.RUNNING]
        want = decide_service(svc, tasks)
        got = decisions.get(sid)
        parity = parity and got is not None \
            and got.create_slots == want.create_slots \
            and got.victim_slots == want.victim_slots
    del store, br, view, steady, decisions

    # ---------------- (b) live update storm ---------------------------
    storm = {}
    s_store = MemoryStore()
    orch = ReplicatedOrchestrator(s_store)
    storm_ok = orch.batched is not None
    orch.start()
    halt = threading.Event()

    def pump():
        while not halt.is_set():
            def cb(tx):
                for t in tx.find_tasks():
                    if t.desired_state == TaskState.RUNNING \
                            and t.status.state < TaskState.RUNNING:
                        c = t.copy()
                        c.status.state = (
                            TaskState.FAILED
                            if t.spec.runtime.image == "v2-poison"
                            else TaskState.RUNNING)
                        tx.update(c)
                    elif t.desired_state >= TaskState.SHUTDOWN \
                            and t.status.state <= TaskState.RUNNING:
                        c = t.copy()
                        c.status.state = TaskState.SHUTDOWN
                        tx.update(c)
            try:
                s_store.update(cb)
            except Exception:
                pass
            halt.wait(0.02)

    pump_t = threading.Thread(target=pump, daemon=True,
                              name="storm-pump")
    pump_t.start()
    sids = [f"st{i:04d}" for i in range(storm_services)]
    poisoned = {sid for sid in sids if rng.random() < 0.25}
    try:
        def seed_storm(batch):
            for sid in sids:
                batch.update(lambda tx, sid=sid: tx.create(
                    mk_service(sid, storm_replicas)))

        s_store.batch(seed_storm)

        def n_running(img=None):
            return sum(
                1 for t in s_store.view(lambda tx: tx.find_tasks())
                if t.status.state == TaskState.RUNNING
                and t.desired_state <= TaskState.RUNNING
                and (img is None or t.spec.runtime.image == img))

        deadline = time.monotonic() + storm_budget_s
        while n_running() < storm_services * storm_replicas:
            if time.monotonic() > deadline:
                storm_ok = False
                break
            time.sleep(0.05)

        import copy as copy_mod
        t0 = time.monotonic()

        def push_all(batch):
            for sid in sids:
                def one(tx, sid=sid):
                    cur = tx.get_service(sid)
                    new = cur.copy()
                    new.previous_spec = copy_mod.deepcopy(cur.spec)
                    new.spec = copy_mod.deepcopy(cur.spec)
                    new.spec.task.runtime.image = (
                        "v2-poison" if sid in poisoned else "v2")
                    new.spec_version = Version(
                        cur.spec_version.index + 1)
                    tx.update(new)
                batch.update(one)

        s_store.batch(push_all)

        def converged(sid):
            svc = s_store.view(lambda tx: tx.get_service(sid))
            state = (svc.update_status or {}).get("state")
            want = ("rollback_completed" if sid in poisoned
                    else "completed")
            if state != want:
                return False
            img = "v1" if sid in poisoned else "v2"
            run = [t for t in s_store.view(
                lambda tx, sid=sid: tx.find_tasks(by.ByServiceID(sid)))
                if t.desired_state <= TaskState.RUNNING
                and t.status.state == TaskState.RUNNING]
            # slot-distinct: a restart racing an update flip can leave
            # a transient duplicate runnable per slot (scalar shares
            # the window; the reaper/agent path resolves it)
            return (len({t.slot for t in run}) == storm_replicas
                    and all(t.spec.runtime.image == img for t in run))

        done: set = set()
        deadline = time.monotonic() + storm_budget_s
        while storm_ok and len(done) < len(sids):
            for sid in sids:
                if sid not in done and converged(sid):
                    done.add(sid)
            if time.monotonic() > deadline:
                storm_ok = False
                break
            time.sleep(0.05)
        storm_s = time.monotonic() - t0
        planner_threads = sum(
            1 for th in threading.enumerate()
            if th.name == "update-wave-planner")
        storm = {
            "services": storm_services,
            "replicas": storm_replicas,
            "rolled_back": len(poisoned),
            "converged": len(done),
            "time_to_converged_s": round(storm_s, 2),
            "planner_threads": planner_threads,
            "planner_stats": dict(orch.updater.planner.stats
                                  if orch.updater.planner else {}),
        }
        storm_ok = storm_ok and planner_threads <= 1
    finally:
        halt.set()
        pump_t.join(timeout=5)
        orch.stop()
    del s_store

    # ---------------- (c) disarmed-plane contract ---------------------
    env_key = "SWARMKIT_TPU_NO_BATCHED_ORCH"
    prev = os.environ.get(env_key)
    os.environ[env_key] = "1"
    try:
        d_store = MemoryStore()
        d_orch = ReplicatedOrchestrator(d_store)
        before = dict(batched_mod.stats)
        d_store.update(lambda tx: tx.create(mk_service("dis0", 1)))
        from swarmkit_tpu.api.objects import EventUpdate
        svc = d_store.view(lambda tx: tx.get_service("dis0"))
        for _ in range(200):
            d_orch.handle(EventUpdate(svc))
            d_orch.flush_events()
        disarmed_plane_calls = sum(
            batched_mod.stats.get(k, 0) - before.get(k, 0)
            for k in set(batched_mod.stats) | set(before))
        d_orch.updater.stop()
        d_orch.restart.stop()
    finally:
        if prev is None:
            os.environ.pop(env_key, None)
        else:
            os.environ[env_key] = prev

    return {
        "parity": bool(parity and steady_ok and storm_ok
                       and disarmed_plane_calls == 0),
        "reconcile": {
            "services": n_services,
            "steady_pass_s": round(steady_pass_s, 4),
            "dirty_pass_s": round(dirty_pass_s, 4),
            "scalar_est_s": round(scalar_est_s, 4),
            "speedup_est_x": round(
                scalar_est_s / max(steady_pass_s, 1e-9), 1),
            "steady_objectless": steady_ok,
            "dirty_services": len(dirty_ids),
        },
        "storm": storm,
        "disarmed_plane_calls": disarmed_plane_calls,
    }


def bench_recovery_plane(np, n_tasks=100_000):
    """Recovery-at-scale row (ISSUE 18): restoring a 100k-task snapshot
    into a fresh store with the versioned columnar section (array
    ADOPTION) vs the same snapshot stripped of it (the pre-18 shape:
    object restore + ColumnarTasks.rebuild's O(objects) upsert walk).
    Also reports the snapshot-stream framing the resumable catch-up
    plane would ship it with (chunks at SNAPSHOT_CHUNK_BYTES). Parity:
    the adopted mirror's canonical snapshot is bit-equal to the rebuild
    oracle's, and the op-count path markers confirm which leg ran."""
    from swarmkit_tpu.api.objects import Node, Task
    from swarmkit_tpu.api.types import NodeStatusState, TaskState
    from swarmkit_tpu.raft.node import SNAPSHOT_CHUNK_BYTES
    from swarmkit_tpu.rpc import codec
    from swarmkit_tpu.store.columnar import ColumnarTasks
    from swarmkit_tpu.store.memory import MemoryStore

    N_NODES = 64
    store = MemoryStore()

    def seed_nodes(tx):
        for i in range(N_NODES):
            node = Node(id=f"rp{i:03d}")
            node.status.state = NodeStatusState.READY
            tx.create(node)
    store.update(seed_nodes)

    def seed_tasks(tx):
        for i in range(n_tasks):
            t = Task(id=f"t{i:07d}", service_id=f"svc{i % 100}",
                     slot=i + 1)
            t.status.state = TaskState.PENDING
            t.desired_state = TaskState.RUNNING
            tx.create(t)
    store.update(seed_tasks)
    store.assign_wave([(f"t{i:07d}", f"rp{i % N_NODES:03d}")
                       for i in range(n_tasks)])

    t0 = time.perf_counter()
    snap = store.save()
    save_s = time.perf_counter() - t0
    blob = codec.dumps(snap)
    n_chunks = max(1, -(-len(blob) // SNAPSHOT_CHUNK_BYTES))

    t0 = time.perf_counter()
    adopted = MemoryStore()
    adopted.restore(snap)
    adopt_s = time.perf_counter() - t0

    legacy_snap = {k: v for k, v in snap.items() if k != "__columnar__"}
    t0 = time.perf_counter()
    rebuilt_store = MemoryStore()
    rebuilt_store.restore(legacy_snap)
    rebuild_s = time.perf_counter() - t0

    parity = (adopted.op_counts.get("restore_columnar_adopted") == 1
              and rebuilt_store.op_counts.get(
                  "restore_columnar_rebuilt") == 1)
    # the isolated LEG comparison: the adoption call vs the rebuild walk
    # it replaces, over the same restored object tables
    tasks = adopted.view(lambda tx: tx.find_tasks())
    services = adopted.view(lambda tx: tx.find_services())
    nodes = adopted.view(lambda tx: tx.find_nodes())
    t0 = time.perf_counter()
    oracle = ColumnarTasks.rebuild(tasks, services=services, nodes=nodes)
    leg_rebuild_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    leg_adopted = ColumnarTasks.adopt(snap["__columnar__"], tasks,
                                      services=services, nodes=nodes)
    leg_adopt_s = time.perf_counter() - t0
    parity = parity and leg_adopted is not None
    parity = parity and ColumnarTasks.snapshots_equal(
        adopted.columnar.snapshot(), oracle.snapshot())
    parity = parity and ColumnarTasks.snapshots_equal(
        adopted.columnar.snapshot(), rebuilt_store.columnar.snapshot())

    return {
        "tasks": n_tasks,
        "save_s": round(save_s, 4),
        "snapshot_bytes": len(blob),
        "stream_chunks": n_chunks,
        "restore_adopt_s": round(adopt_s, 4),
        "restore_rebuild_s": round(rebuild_s, 4),
        "restore_speedup_x": round(rebuild_s / max(adopt_s, 1e-9), 2),
        "leg_rebuild_s": round(leg_rebuild_s, 4),
        "leg_adopt_s": round(leg_adopt_s, 4),
        "columnar_leg_speedup_x": round(
            leg_rebuild_s / max(leg_adopt_s, 1e-9), 2),
        "parity": parity,
    }


def bench_log_fanout_storm(np, n_subs=100_000, rounds=3, batch=32,
                           slow_frac=0.01, slow_limit=8,
                           permsg_subs=10_000, parity_subs=64,
                           parity_seed=7):
    """Log fan-out plane acceptance row (ISSUE 20): an `n_subs`-
    subscriber publish storm against the sharded broker (driven —
    offers inline, so throughput numbers measure the fan-out path, not
    thread scheduling). Gates:

    * ZERO-LOSS for in-limit subscribers (default client bound, drained
      each round): delivered == published, shed == 0;
    * EXACT shed accounting on the slow cohort (tiny bound, never
      drained): delivered + shed == published per subscriber, and the
      in-stream LogShedRecord window matches the shed count with the
      stream resuming after it;
    * batched delivery >= 10x the per-message fan-out on the same
      shapes (one publish_logs burst of `batch` vs `batch` single-
      message calls);
    * `disarmed_publish_allocs == 0` — the telemetry-off storm never
      calls the armed recorder (spy on _record_publish + the registry
      snapshot builder, the telemetry_plane discipline);
    * a seeded sharded ≡ single-plane wire-parity mini-run (order-
      normalized streams + completion records; the 20-seed fuzz lives
      in tests/test_logbroker_sharded.py).
    Lag p99 (publish-call completion minus batch build stamp) is
    reported for the bounded-lag acceptance."""
    from swarmkit_tpu.api.objects import Task as _Task
    from swarmkit_tpu.logbroker.broker import (
        LogBroker,
        LogMessage,
        LogSelector,
        LogShedRecord,
        SubscriptionComplete,
        make_log_message,
    )
    from swarmkit_tpu.logbroker.sharded import ShardedLogBroker, ShedChannel
    from swarmkit_tpu.store.memory import MemoryStore
    from swarmkit_tpu.utils import telemetry
    from swarmkit_tpu.utils.metrics import snapshot_counter_value
    from swarmkit_tpu.utils.slo import quantile_nearest_rank

    store = MemoryStore()
    task = _Task(id="t-log", service_id="svc-log", slot=1)
    task.node_id = "n-log"
    store.update(lambda tx: tx.create(task))
    sel = LogSelector(service_ids=["svc-log"])

    broker = ShardedLogBroker(store)
    broker.listen_subscriptions("n-log")
    n_slow = max(1, int(n_subs * slow_frac))
    t0 = time.perf_counter()
    subs = [broker.subscribe_logs(sel, follow=True,
                                  limit=(slow_limit if i < n_slow else -1))
            for i in range(n_subs)]
    subscribe_s = time.perf_counter() - t0

    # disarmed-cost spies (telemetry_plane discipline): the storm below
    # runs with the plane off; one armed-recorder call is a failure
    spy = {"records": 0, "snaps": 0}
    orig_record = broker._record_publish
    broker._record_publish = (
        lambda *a, **k: spy.__setitem__("records", spy["records"] + 1))
    from swarmkit_tpu.utils import metrics as metrics_mod
    orig_snap_builder = metrics_mod.registry_snapshot
    metrics_mod.registry_snapshot = (
        lambda *a, **k: (spy.__setitem__("snaps", spy["snaps"] + 1),
                         orig_snap_builder(*a, **k))[1])

    lag_samples = []
    lag_every = max(1, n_subs // 64)
    t0 = time.perf_counter()
    try:
        for r in range(rounds):
            msgs = [make_log_message(task, "stdout", b"x" * 16)
                    for _ in range(batch)]
            stamp = msgs[-1].timestamp
            for i, (sid, ch) in enumerate(subs):
                broker.publish_logs(sid, msgs)
                if i % lag_every == 0:
                    lag_samples.append(max(0.0, time.time() - stamp))
            # in-limit subscribers drain between rounds (the consumer
            # half of "in-limit"); the slow cohort never does
            for _sid, ch in subs[n_slow:]:
                ch.drain()
    finally:
        broker._record_publish = orig_record
        metrics_mod.registry_snapshot = orig_snap_builder
    batched_s = time.perf_counter() - t0
    batched_msgs = n_subs * batch * rounds

    # per-message fan-out on the same shapes, a subsample scaled up
    pm_subs = subs[n_slow:n_slow + min(permsg_subs, n_subs - n_slow)]
    pm_msgs = [make_log_message(task, "stdout", b"x" * 16)
               for _ in range(batch)]
    t0 = time.perf_counter()
    for sid, ch in pm_subs:
        for m in pm_msgs:
            broker.publish_logs(sid, [m])
    permsg_s = time.perf_counter() - t0
    for _sid, ch in pm_subs:
        ch.drain()
    batched_rate = batched_msgs / max(batched_s, 1e-9)
    permsg_rate = (len(pm_subs) * batch) / max(permsg_s, 1e-9)

    # accounting gates
    zero_loss = all(ch.shed == 0 and ch.delivered == ch.published
                    for _sid, ch in subs[n_slow:])
    acct_exact = all(ch.delivered + ch.shed == ch.published
                     for _sid, ch in subs)
    shed_total = sum(ch.shed for _sid, ch in subs)
    # shed-and-resume on one slow subscriber: the drained stream must
    # carry ONE pending window marker with the exact count, then resume
    slow_sid, slow_ch = subs[0]
    pre_shed = slow_ch.shed
    drained = slow_ch.drain()
    markers = [m for m in drained if isinstance(m, LogShedRecord)]
    resume_ok = (len(markers) == 1 and markers[0].count == pre_shed
                 and pre_shed > 0)
    broker.publish_logs(slow_sid, [make_log_message(task, "stdout", b"r")])
    resumed = slow_ch.drain()
    resume_ok = resume_ok and len(resumed) == 1 and isinstance(
        resumed[0], LogMessage)
    snap = broker.metrics_snapshot()
    snap_exact = snap["published"] == snap["delivered"] + snap["shed"]

    # armed leg: the families populate and the disarmed spies were cold
    with telemetry.armed():
        broker.publish_logs(subs[-1][0],
                            [make_log_message(task, "stdout", b"a")])
    armed_published = snapshot_counter_value(
        metrics_mod.registry_snapshot(),
        "swarm_logbroker_published_total",
        (str(stable_shard_for_bench("n-log", broker.shards)),))

    # sharded ≡ single-plane wire parity, one seeded mini-run (the
    # 20-seed fuzz is tier-1); order-normalized per-subscription streams
    wire_parity = _log_wire_parity_run(np, parity_subs, parity_seed)

    parity = bool(zero_loss and acct_exact and resume_ok and snap_exact
                  and wire_parity and spy["records"] == 0
                  and spy["snaps"] == 0 and armed_published >= 1)
    return {
        "parity": parity,
        "subs": n_subs,
        "slow_subs": n_slow,
        "rounds": rounds,
        "batch": batch,
        "shards": broker.shards,
        "subscribe_s": round(subscribe_s, 4),
        "published_total": snap["published"],
        "delivered_total": snap["delivered"],
        "shed_total": shed_total,
        "zero_loss_in_limit": zero_loss,
        "shed_accounting_exact": acct_exact,
        "shed_resume_ok": resume_ok,
        "snapshot_accounting_exact": snap_exact,
        "wire_parity": wire_parity,
        "batched_msgs_per_s": round(batched_rate, 1),
        "per_message_msgs_per_s": round(permsg_rate, 1),
        "batched_speedup_x": round(batched_rate / max(permsg_rate, 1e-9),
                                   2),
        "lag_p99_s": round(quantile_nearest_rank(lag_samples, 99) or 0.0,
                           6),
        "disarmed_publish_allocs": spy["records"] + spy["snaps"],
        "armed_publish_records": armed_published,
    }


def stable_shard_for_bench(node_id, shards):
    from swarmkit_tpu.dispatcher.heartbeat import stable_shard

    return stable_shard(node_id, shards)


def _log_wire_parity_run(np, n_subs, seed):
    """One seeded op sequence driven against BOTH broker planes
    (un-started — deterministic), comparing per-subscription client
    streams (message payload sequences — publish order is program
    order, so exact equality) and completion records (error fragments
    order-normalized: the two planes may iterate notify sets
    differently)."""
    from swarmkit_tpu.api.objects import Task as _Task
    from swarmkit_tpu.logbroker.broker import (
        LogBroker,
        LogMessage,
        LogSelector,
        SubscriptionComplete,
        make_log_message,
    )
    from swarmkit_tpu.logbroker.sharded import ShardedLogBroker
    from swarmkit_tpu.store.memory import MemoryStore

    def run(make_broker):
        rng = np.random.default_rng(seed)
        store = MemoryStore()
        tasks = []
        for i in range(8):
            t = _Task(id=f"pt{i}", service_id=f"psvc{i % 4}", slot=i + 1)
            t.node_id = f"pn{i % 4}"
            tasks.append(t)
        store.update(lambda tx: [tx.create(t) for t in tasks])
        broker = make_broker(store)
        for i in range(3):          # pn3 never listens
            broker.listen_subscriptions(f"pn{i}")
        streams = {}
        subs = []
        for i in range(n_subs):
            follow = bool(rng.integers(0, 2))
            svc = f"psvc{int(rng.integers(0, 4))}"
            sid, ch = broker.subscribe_logs(
                LogSelector(service_ids=[svc]), follow=follow, limit=None)
            subs.append((i, sid, ch, svc))
        for i, sid, ch, svc in subs:
            t = tasks[int(rng.integers(0, 8))]
            k = int(rng.integers(1, 5))
            broker.publish_logs(sid, [
                make_log_message(t, "stdout", bytes([i % 251, j]))
                for j in range(k)])
            if rng.integers(0, 3) == 0:
                broker.publish_logs(sid, [], node_id=t.node_id, close=True,
                                    error=("pump died"
                                           if rng.integers(0, 2) else ""))
        prefix = ("warning: incomplete log stream. some logs could not "
                  "be retrieved for the following reasons: ")
        for i, sid, ch, svc in subs:
            out = ch.drain()
            data = tuple(m.data for m in out if isinstance(m, LogMessage))
            comp = [m for m in out if isinstance(m, SubscriptionComplete)]
            err = None
            if comp:
                text = comp[0].error
                if text.startswith(prefix):
                    text = text[len(prefix):]
                # order-normalized: the planes may iterate notify sets
                # (and therefore record warnings) in different orders
                err = tuple(sorted(text.split(", "))) if text else ()
            streams[i] = (data, err, ch.closed)
        return streams

    return run(lambda s: LogBroker(s)) == run(lambda s: ShardedLogBroker(s))


def bench_host_micro(np):
    """The BASELINE.md harness rows the reference ships benchmarks for
    but no numbers (store ops memory_test.go:2028-2120, watch queue at
    10k subscribers watch_test.go:153-216, remotes Select/Observe
    remotes_test.go:337-379). Host-side work by design — the control
    plane's bookkeeping, not kernel math — measured here so the table
    has numbers."""
    import random as _random

    from swarmkit_tpu.api.objects import Node
    from swarmkit_tpu.remotes.remotes import Remotes
    from swarmkit_tpu.store.memory import MemoryStore
    from swarmkit_tpu.store.watch import WatchQueue

    out = {}

    # ---- store ops (create / update / get / find-by-name) ---------------
    store = MemoryStore()
    N = 10_000
    nodes = [Node(id=f"bench-node-{i:05d}") for i in range(N)]
    for n in nodes:
        n.spec.annotations.name = n.id
    t0 = time.perf_counter()
    def create_all(tx):
        for n in nodes:
            tx.create(n)
    store.update(create_all)
    create_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    def update_all(tx):
        for n in nodes:
            cur = tx.get_node(n.id).copy()
            cur.spec.annotations.labels = {"touched": "1"}
            tx.update(cur)
    store.update(update_all)
    update_s = time.perf_counter() - t0

    view = store.view()

    def timed(fn, reps=5):
        # min-of-batches: these loops finish in single-digit ms, below
        # the jitter bound (CLAUDE.md)
        best = None
        for _ in range(reps):
            t0 = time.perf_counter()
            fn()
            dt = time.perf_counter() - t0
            best = dt if best is None or dt < best else best
        return best

    get_s = timed(lambda: [view.get_node(n.id) for n in nodes])

    from swarmkit_tpu.store import by
    find_s = timed(lambda: [view.find_nodes(by.ByName(f"bench-node-{i:05d}"))
                            for i in range(0, N, 10)])

    out["store_ops"] = {
        "create_per_s": round(N / create_s),
        "update_per_s": round(N / update_s),
        "get_per_s": round(N / get_s),
        "find_by_name_per_s": round((N // 10) / find_s),
    }

    # bulk-create at the reference grid's 100k-node scale (the round-2
    # O(n²)→O(1) name-uniqueness fix is what makes this row feasible)
    store_big = MemoryStore()
    big = [Node(id=f"bench-bignode-{i:06d}") for i in range(100_000)]
    for n in big:
        n.spec.annotations.name = n.id
    t0 = time.perf_counter()

    def create_big(tx):
        for n in big:
            tx.create(n)
    store_big.update(create_big)
    out["store_ops_100k"] = {
        "create_per_s": round(len(big) / (time.perf_counter() - t0))}

    # ---- watch queue: 10k subscribers, 4 publishers ---------------------
    # two regimes: per-event publish (the reference bench's shape,
    # watch_test.go:153-216) and batched publish_all — the store's actual
    # per-commit delivery path (store/memory.py uses publish_all)
    import threading

    q = WatchQueue(default_limit=None)
    subs = [q.watch(limit=None) for _ in range(10_000)]
    EVENTS, PUBS = 400, 4
    t0 = time.perf_counter()
    ts = [threading.Thread(
        target=lambda: [q.publish(object()) for _ in range(EVENTS // PUBS)])
        for _ in range(PUBS)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    fanout_s = time.perf_counter() - t0
    delivered = EVENTS * len(subs)
    drained = sum(len(s.drain()) for s in subs[:10]) * (len(subs) // 10)

    BATCH = 25                      # a store commit's event batch
    t0 = time.perf_counter()
    ts = [threading.Thread(
        target=lambda: [q.publish_all([object()] * BATCH)
                        for _ in range(EVENTS // PUBS // BATCH)])
        for _ in range(PUBS)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    batch_s = time.perf_counter() - t0
    for s in subs[:10]:
        s.drain()
    q.close()
    out["watch_queue_10k_subs"] = {
        "published": EVENTS, "subscribers": len(subs),
        "deliveries_per_s": round(delivered / fanout_s),
        "publish_s": round(fanout_s, 4),
        "batch_size": BATCH,
        "batched_deliveries_per_s": round(delivered / batch_s),
        "batched_publish_s": round(batch_s, 4),
        "sanity_drained_estimate": drained,
    }

    # ---- heartbeat timers at the 10k-node design point ------------------
    # (survey §7 hard part: per-node timers must ride a shared wheel, not
    # one thread each — threading.Timer at 10k nodes is 10k threads)
    import threading as _threading

    from swarmkit_tpu.dispatcher.heartbeat import Heartbeat, HeartbeatWheel

    hbs = [Heartbeat(60.0, lambda: None) for _ in range(10_000)]
    threads_before = _threading.active_count()
    t0 = time.perf_counter()
    for hb in hbs:
        hb.start()
    arm_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(5):
        for hb in hbs:
            hb.beat()
    beat_s = time.perf_counter() - t0
    extra_threads = _threading.active_count() - threads_before
    for hb in hbs:
        hb.stop()

    # the dispatcher's session plane (ISSUE 4): ONE coarse-bucketed
    # wheel, beat() = dict write — vs the per-timer cancel/re-arm above
    wheel = HeartbeatWheel(granularity=0.5)
    keys = [f"wn{i:05d}" for i in range(10_000)]
    t0 = time.perf_counter()
    for k in keys:
        wheel.add(k, 60.0, lambda: None)
    wheel_arm_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(5):
        for k in keys:
            wheel.beat(k)
    wheel_beat_s = time.perf_counter() - t0
    wheel.stop()
    # beat-arrival dispersion (VERDICT item 6): the dispatcher returns
    # period − uniform(0, ε) per beat, so a herd registered in a burst
    # spreads across the ε window instead of beating in phase forever
    from swarmkit_tpu.dispatcher.dispatcher import (
        Dispatcher as _Dispatcher,
        HEARTBEAT_EPSILON,
    )
    from swarmkit_tpu.store.memory import MemoryStore as _MS

    _disp = _Dispatcher(_MS(), heartbeat_period=5.0)
    jit = np.array([_disp._jittered_period() for _ in range(10_000)])
    out["heartbeat_10k_nodes"] = {
        "arm_per_s": round(10_000 / arm_s),
        "beat_per_s": round(50_000 / beat_s),
        "wheel_arm_per_s": round(10_000 / wheel_arm_s),
        "wheel_beat_per_s": round(50_000 / wheel_beat_s),
        "extra_threads": extra_threads,
        "beat_dispersion_s": round(float(jit.std()), 4),
        "beat_window_s": [round(float(jit.min()), 4),
                          round(float(jit.max()), 4)],
        "epsilon_s": HEARTBEAT_EPSILON,
    }

    # ---- remotes Select/Observe at 3..27 peers --------------------------
    rng = _random.Random(3)
    rem = {}
    for peers in (3, 9, 27):
        r = Remotes(*[f"10.0.0.{i}:4242" for i in range(peers)],
                    rng=rng)
        t0 = time.perf_counter()
        for _ in range(100_000):
            r.select()
        sel_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        for i in range(100_000):
            r.observe(f"10.0.0.{i % peers}:4242",
                      1 if i % 7 else -1)
        obs_s = time.perf_counter() - t0
        rem[f"peers_{peers}"] = {
            "select_per_s": round(100_000 / sel_s),
            "observe_per_s": round(100_000 / obs_s),
        }
    out["remotes"] = rem
    # host bookkeeping has no CPU-vs-TPU parity question; the key exists
    # so the strict placement_parity aggregate stays strict
    out["parity"] = True
    return out


def _run_row(name, thunk):
    """Per-row fault isolation (VERDICT r03 item 2): one row's crash must
    not zero the whole artifact. A failed row carries its own exception +
    traceback tail; the aggregate marks parity false but every other row
    still reports real numbers. Progress goes to stderr so a wedged run
    shows how far it got (the reference's swarm-bench collector reports
    progressively, cmd/swarm-bench/collector.go)."""
    import traceback

    print(f"bench: running {name} ...", file=sys.stderr, flush=True)
    t0 = time.perf_counter()
    try:
        row = thunk()
        print(f"bench: {name} done in {time.perf_counter() - t0:.1f}s "
              f"parity={row.get('parity')}", file=sys.stderr, flush=True)
        return row
    except Exception as exc:
        tb = traceback.format_exc()
        print(f"bench: {name} FAILED after {time.perf_counter() - t0:.1f}s: "
              f"{exc!r}\n{tb}", file=sys.stderr, flush=True)
        return {
            "parity": False,
            "error": repr(exc),
            "traceback_tail": tb.strip().splitlines()[-12:],
            "elapsed_s": round(time.perf_counter() - t0, 1),
        }


def main():
    import numpy as np

    import jax
    from swarmkit_tpu.ops import placement as placement_ops
    from swarmkit_tpu.scheduler import batch

    # --sync-commit reverts every pipelined row to the round-5
    # synchronous commit (unchanged numbers); the default rides the
    # async commit plane (ops/commit.py)
    ac = "--sync-commit" not in sys.argv[1:]

    def sched(*a, **kw):
        kw.setdefault("async_commit", ac)
        return bench_scheduler_config(np, placement_ops, batch, *a, **kw)

    # e2e FIRST, on a clean heap: the live-cluster row spawns an
    # in-process 3-manager raft + 5 workers; after the grid configs the
    # process carries multi-GB of wave objects and GC pauses stall raft
    # writes past their timeouts (observed: create_service timeout when
    # this ran last)
    rows = [
        ("e2e_service_start_100r_3m_5w", lambda: bench_e2e_service_start(np)),
        # burst rows next, still on a small heap: measured at the END of
        # the grid their per-round host time carries multi-GB-heap GC
        # pauses (observed: global diff 1.28x / replay 2.6x when last vs
        # 1.95x / 3.1x standalone) — same clean-heap rationale as e2e
        ("global_diff_50svc_x_10k", lambda: bench_global_diff(np)),
        ("raft_replay_1m_x_5", lambda: bench_raft_replay(np)),
        # round 6: the raft GROUP-COMMIT plane (batched Ready flush +
        # segmented-WAL fsync coalescing + pipelined proposals) on a live
        # in-process 3-manager cluster; still on a small heap
        ("raft_backed_store_1x3", lambda: bench_raft_backed_store(np)),
        # round 7 (ISSUE 7): the fused flagship on the device mesh at the
        # scale-out grid — 131k+ nodes × 1M tasks, sampled-shard parity
        ("mesh_cluster_step", lambda: bench_mesh_cluster_step(np)),
        # ISSUE 19: spread vs binpack vs topology through the same kernel,
        # parity gated at steady-tick + mid-size scale-out shapes
        ("strategy_grid", lambda: bench_strategy_grid(np)),
        # waves=7 -> three fully-pipelined periods in the e2e sample
        # (depth+1..waves-1); with one sample the min-estimator was a
        # lottery against heap/tunnel noise on the commit-heavy wall
        ("grid_100k_x_10k", lambda: sched(
            N_NODES, N_TASKS, N_SERVICES,
            waves=7)),
        ("constraint_heavy_1k_x_1k", lambda: sched(
            1_000, 1_000, 20,
            constraint_heavy=True)),
        ("binpack_10k_x_1k", lambda: sched(
            1_000, 10_000, 50, binpack=True)),
        # the reference benchScheduler grid (scheduler_test.go:3187-3209)
        ("grid_1k_x_1k", lambda: sched(
            1_000, 1_000, 20)),
        ("grid_10k_x_1k", lambda: sched(
            1_000, 10_000, 20)),
        ("grid_100k_x_1k", lambda: sched(
            1_000, 100_000, 20)),
        ("grid_1m_x_10k", lambda: sched(
            10_000, 1_000_000, 100)),
        # the reference grid's 100k-NODE half (scheduler_test.go:3187-3209):
        # 100k nodes x 1k / 100k / 1M tasks
        ("grid_1k_x_100k", lambda: sched(
            100_000, 1_000, 20)),
        ("grid_100k_x_100k", lambda: sched(
            100_000, 100_000, 20)),
        ("grid_1m_x_100k", lambda: sched(
            100_000, 1_000_000, 100, waves=4,
            depth=2)),
        # the plugin-constrained grid (scheduler_test.go:3210-3226):
        # 1-in-3 nodes carry the required volume plugin
        ("plugin_1k_x_1k", lambda: sched(
            1_000, 1_000, 20,
            plugin_every=3, plugin_volume=True)),
        ("plugin_10k_x_1k", lambda: sched(
            1_000, 10_000, 20,
            plugin_every=3, plugin_volume=True)),
        ("plugin_100k_x_1k", lambda: sched(
            1_000, 100_000, 20,
            plugin_every=3, plugin_volume=True)),
        ("plugin_100k_x_5k", lambda: sched(
            5_000, 100_000, 20,
            plugin_every=3, plugin_volume=True)),
        # the assignment-diff plane at the 10k-node design point
        # (VERDICT item 7)
        ("dispatcher_fanout_10k", lambda: bench_dispatcher_fanout(np)),
        # ISSUE 13: the SHARDED flush plane at a 100k-session storm
        # (per-shard columns at P∈{1,4,8} + follower_read_ratio)
        ("dispatcher_fanout_storm_100k",
         lambda: bench_dispatcher_fanout_storm(np)),
        # ISSUE 11: columnar vs object-store wave write-back at
        # 100k/1M tasks (>=10x acceptance + rebuild bit-equality)
        ("store_plane", lambda: bench_store_plane(np)),
        ("host_micro", lambda: bench_host_micro(np)),
        # ISSUE 5: per-stage breakdown via the trace plane + the
        # disarmed-overhead acceptance (zero span allocs with tracing off)
        ("trace_plane", lambda: bench_trace_plane(np)),
        # ISSUE 8: lockgraph disarmed-cost acceptance (plain primitive,
        # zero wrapper allocs) + the tree-wide lint/mirror clean gate
        ("lint_plane", lambda: bench_lint_plane(np)),
        # ISSUE 10: lifecycle-plane disarmed-cost acceptance (zero
        # timeline records on the wave + flush paths; one batched
        # scheduler record per wave) + armed e2e timeline slice
        ("slo_plane", lambda: bench_slo_plane(np)),
        # ISSUE 15: telemetry-plane disarmed-cost acceptance (zero
        # snapshot builds/stores on the beat path), armed piggyback
        # overhead per beat, 10k-node rollup merge throughput, and the
        # driven parity + staleness gate
        ("telemetry_plane", lambda: bench_telemetry_plane(np)),
        # ISSUE 14: batched orchestration plane — 100k-service columnar
        # reconcile pass (objectless steady classification + decision
        # parity on the dirty subset), the live rolling-update storm on
        # the shared wave planner (one thread, auto-rollback share),
        # and the disarmed-plane zero-alloc contract
        ("orchestrator_storm", lambda: bench_orchestrator_storm(np)),
        # ISSUE 18: recovery plane — columnar-adoption restore vs the
        # object-walk rebuild at 100k tasks, plus the stream framing
        # the resumable snapshot catch-up ships the same blob with
        ("recovery_restore_100k", lambda: bench_recovery_plane(np)),
        # ISSUE 20: log fan-out plane — 100k-subscriber publish storm
        # (zero-loss for in-limit subscribers, exact shed accounting,
        # batched delivery vs per-message fan-out, disarmed publish
        # allocs == 0, sharded ≡ scalar wire parity)
        ("log_fanout_storm_100k", lambda: bench_log_fanout_storm(np)),
    ]
    configs = {name: _run_row(name, thunk) for name, thunk in rows}
    ns = configs["grid_100k_x_10k"]   # the north star IS this grid config

    parity = all(c.get("parity", False) for c in configs.values())
    # a row that RAN but regressed parity is a failed row too (ISSUE 6):
    # recording {"parity": false} deep in the JSON while exiting 0 let a
    # steady-tick parity regression ride a green bench — failed_rows +
    # the nonzero exit below make it loud
    failed_rows = sorted(n for n, c in configs.items()
                         if "error" in c or not c.get("parity", False))
    # headline: the largest reference-grid config (scheduler_test.go's grid
    # reaches 1M tasks) — end-to-end including encode + all transfers +
    # slot-order materialization, bit-identical placements required
    head = configs["grid_1m_x_10k"]
    if "error" in head:               # fall back so value/vs_baseline exist
        head = {"placed": 0, "tpu_tick_s": 1.0, "speedup": 0.0}
    result = {
        "metric": ("tasks scheduled/sec, steady full tick at 1M tasks x "
                   "10k nodes; placement parity vs CPU path"),
        "value": round(head["placed"] / head["tpu_tick_s"], 1),
        "unit": "tasks/s",
        "vs_baseline": head["speedup"],
        "detail": {
            "device": str(jax.devices()[0]),
            "north_star": ns,
            "configs": configs,
            "placement_parity": parity,
            "failed_rows": failed_rows,
            "north_star_under_1s": bool(
                "error" not in ns and ns["tpu_tick_s"] < 1.0),
            "commit_mode": "async" if ac else "sync",
            "note": ("steady ticks run on device-RESIDENT node state "
                     "(ops/resident.py) through the tick PIPELINE "
                     "(ops/pipeline.py): deltas up, sliced int16 counts "
                     "down, with the counts D2H overlapped under the "
                     "previous wave's commit (one add_task per placement "
                     "+ slot materialization) — so device_s is the "
                     "dispatch + pull residual, near zero when the commit "
                     "window covers the transfer. Round 6: the commit's "
                     "heavy half additionally rides the ASYNC COMMIT "
                     "PLANE (ops/commit.py; --sync-commit reverts), so a "
                     "steady period's wall charges only the barrier "
                     "residual the overlap failed to hide "
                     "(commit_overlap_s = the hidden seconds). "
                     "e2e_wave_s/cpu_e2e_wave_s compare full wave "
                     "periods including that shared commit work. "
                     "Cold ticks pay the full "
                     "encode + upload serially. kernel_resident_s is the "
                     "pure device-resident fill a PCIe-attached host "
                     "would see. Placements are bit-identical to the CPU "
                     "oracle in every config."),
        },
    }
    print(json.dumps(result))
    if not parity:
        sys.exit(1)


if __name__ == "__main__":
    main()
